"""Prefix-cache-aware routing as a first-class router policy.

Reference: serve routing_policies/prefix_aware/prefix_aware_router.py —
route requests that share a prompt prefix to the same replica so their KV
prefixes stay warm on one engine. Promoted here from the ``LLMHandle``
one-off (which hashed ``md5(key) % n_replicas``: ANY replica-set change
remapped essentially every key, cold-starting every KV cache at once)
into a shared policy with

* a **consistent-hash ring** (virtual nodes per replica), so a replica
  joining or leaving moves only ~1/N of the key space while every other
  prefix keeps hitting its warm replica;
* **cache-hit accounting** on the shared metrics registry
  (``ray_tpu.serve.prefix_cache_hits`` / ``_misses``): a routing
  decision is a "hit" when the key lands on the same replica as its
  previous request (bounded LRU of recent keys), which is exactly the
  warm-KV expectation the policy exists to maximize.

``DeploymentHandle.remote_with_key`` routes through this policy; plain
``options(routing_policy="prefix")`` handles derive the key from the
request body's prompt/messages prefix automatically.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from collections import OrderedDict
from typing import Any, List, Optional, Sequence

_obs_lock = threading.Lock()
_obs_metrics: Optional[dict] = None


def _obs() -> dict:
    global _obs_metrics
    with _obs_lock:
        if _obs_metrics is None:
            from ray_tpu.util.metrics import Counter

            _obs_metrics = {
                "hits": Counter(
                    "ray_tpu.serve.prefix_cache_hits",
                    "prefix-routed requests that landed on the same "
                    "replica as the previous request for that key"),
                "misses": Counter(
                    "ray_tpu.serve.prefix_cache_misses",
                    "prefix-routed requests that moved to a different "
                    "replica (first sight of the key, or ring churn)"),
            }
        return _obs_metrics


def _hash64(data: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(data.encode(), digest_size=8).digest(), "little")


def _replica_id(replica: Any) -> str:
    """Stable identity for a replica across handle refreshes (the actor
    id survives topology re-fetches; id() of the handle object does not)."""
    actor_id = getattr(replica, "_actor_id", None)
    if actor_id is not None:
        return actor_id.hex() if hasattr(actor_id, "hex") else str(actor_id)
    return repr(replica)


class ConsistentHashRing:
    """Classic consistent hashing with virtual nodes: each replica owns
    ``vnodes`` points on a 64-bit ring; a key maps to the first point
    clockwise. Adding/removing one replica remaps only the key ranges
    adjacent to its points (~1/N of the space)."""

    def __init__(self, replicas: Sequence[Any], vnodes: int = 64):
        self.vnodes = vnodes
        self._points: List[int] = []
        self._owners: List[Any] = []
        for replica in replicas:
            rid = _replica_id(replica)
            for v in range(vnodes):
                point = _hash64(f"{rid}:{v}")
                idx = bisect.bisect(self._points, point)
                self._points.insert(idx, point)
                self._owners.insert(idx, replica)

    def __len__(self) -> int:
        return len(self._points)

    def lookup(self, key: str) -> Any:
        if not self._points:
            raise ValueError("empty hash ring")
        idx = bisect.bisect(self._points, _hash64(key)) % len(self._points)
        return self._owners[idx]


class PrefixRouter:
    """Key -> replica policy for one deployment: consistent-hash lookup
    plus hit/miss accounting against the key's previous assignment."""

    def __init__(self, deployment_name: str, prefix_len: int = 64,
                 vnodes: int = 64, history: int = 4096):
        self._name = deployment_name
        self.prefix_len = prefix_len
        self._vnodes = vnodes
        self._ring: Optional[ConsistentHashRing] = None
        self._ring_version: Optional[int] = None
        # bounded LRU: key -> replica id of its last routing decision
        self._last: OrderedDict = OrderedDict()
        self._history = history
        self._lock = threading.Lock()

    def key_of(self, body: Any) -> Optional[str]:
        """Derive the routing key from a request body: the prompt (or
        flattened messages) prefix. None -> caller should fall back to
        its default policy."""
        if isinstance(body, dict):
            prompt = body.get("prompt") or str(body.get("messages", ""))
        elif isinstance(body, str):
            prompt = body
        else:
            return None
        return prompt[: self.prefix_len] if prompt else None

    def pick(self, key: str, replicas: Sequence[Any],
             version: Optional[int] = None) -> Any:
        """Route ``key`` over the CURRENT replica set. The ring rebuilds
        only when the topology version moves; hit/miss counters compare
        against the key's previous assignment."""
        with self._lock:
            if self._ring is None or self._ring_version != version \
                    or len(self._ring) != len(replicas) * self._vnodes:
                self._ring = ConsistentHashRing(replicas,
                                                vnodes=self._vnodes)
                self._ring_version = version
            replica = self._ring.lookup(key)
            rid = _replica_id(replica)
            prev = self._last.pop(key, None)
            self._last[key] = rid
            if len(self._last) > self._history:
                self._last.popitem(last=False)
        obs = _obs()
        if prev is None or prev != rid:
            obs["misses"].inc(tags={"deployment": self._name})
        else:
            obs["hits"].inc(tags={"deployment": self._name})
        return replica

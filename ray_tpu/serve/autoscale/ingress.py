"""SLO-aware ingress/admission plane at the handle tier.

Reference shape: the serve proxy + request router keep per-deployment
queues and apply backpressure; production LLM gateways add per-tenant
fairness and explicit load shedding. Three pieces:

* :class:`SLOConfig` — per-route targets. ``queue_target_s`` is defended
  by the autoscaler (queue-wait p99 over it reads as up-pressure) and the
  GCS health monitor flags routes whose observed p99s exceed their
  registered targets; ``latency_budget_s`` is the admission deadline —
  a request still queued past it is shed instead of dispatched doomed.
* :class:`FairQueue` — deficit-round-robin across tenant/session keys
  with BOUNDED per-tenant queues. A full queue sheds synchronously
  (:class:`LoadShedError`) instead of growing an unbounded backlog; a
  2x-weight tenant drains twice as fast, and one flooding tenant can
  only ever occupy its own bound, never another tenant's throughput.
* :class:`IngressHandle` — wraps a DeploymentHandle: ``submit()`` returns
  a ``concurrent.futures.Future``; a dispatcher thread admits queued
  requests whenever in-flight capacity frees (replicas x
  ``max_inflight_per_replica``), and one completer thread resolves ALL
  outstanding refs through a single vectorized ``ray_tpu.wait`` poll —
  no per-request waiter threads.

Everything observable lands on the shared metrics registry
(``ray_tpu.serve.queue_depth`` / ``ray_tpu.serve.shed_requests`` /
``ray_tpu.serve.admitted_requests``) and therefore in the GCS
metrics-history ring.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, Optional

_obs_lock = threading.Lock()
_obs_metrics: Optional[dict] = None


def _obs() -> dict:
    global _obs_metrics
    with _obs_lock:
        if _obs_metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _obs_metrics = {
                "queue_depth": Gauge(
                    "ray_tpu.serve.queue_depth",
                    "requests waiting in the ingress fair queue"),
                "shed": Counter(
                    "ray_tpu.serve.shed_requests",
                    "requests rejected by admission control (full tenant "
                    "queue or expired latency budget)"),
                "admitted": Counter(
                    "ray_tpu.serve.admitted_requests",
                    "requests dispatched to replicas by the ingress"),
            }
        return _obs_metrics


class LoadShedError(RuntimeError):
    """Explicit load-shed response: the ingress refused (or abandoned)
    the request instead of queueing it unboundedly. Callers should treat
    it as retryable-after-backoff (HTTP 503 semantics)."""


@dataclass
class SLOConfig:
    """Per-route service-level objectives registered with the serve
    controller (and through it, the GCS health monitor)."""

    ttft_target_s: Optional[float] = None
    queue_target_s: Optional[float] = None
    latency_budget_s: Optional[float] = None
    max_queue_depth: int = 256
    tenant_weights: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SLOConfig":
        unknown = set(d) - set(cls.__dataclass_fields__)
        if unknown:
            raise ValueError(f"unknown slo keys: {sorted(unknown)}")
        return cls(**d)


class FairQueue:
    """Deficit-round-robin fair queue over tenant keys (thread-safe).

    Unit-cost DRR: each visit tops a tenant's deficit up by
    ``quantum x weight`` and drains items while the deficit covers them,
    so long-run throughput shares converge to the weight ratio while
    per-tenant order stays FIFO. Bounded per-tenant depth: ``push`` on a
    full queue returns False (the ingress sheds instead of buffering)."""

    def __init__(self, max_depth_per_tenant: int = 256,
                 quantum: float = 1.0,
                 weights: Optional[Dict[str, float]] = None):
        self.max_depth = int(max_depth_per_tenant)
        self.quantum = float(quantum)
        self._weights = dict(weights or {})
        self._queues: Dict[str, deque] = {}
        self._deficit: Dict[str, float] = {}
        self._active: deque = deque()  # tenant visit order
        self._visiting: Optional[str] = None
        self._lock = threading.Lock()

    def weight(self, tenant: str) -> float:
        return max(float(self._weights.get(tenant, 1.0)), 1e-6)

    def __len__(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def depths(self) -> Dict[str, int]:
        with self._lock:
            return {t: len(q) for t, q in self._queues.items() if q}

    def push(self, tenant: str, item: Any) -> bool:
        with self._lock:
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
            if len(q) >= self.max_depth:
                return False
            if not q and tenant not in self._active:
                self._active.append(tenant)
            q.append(item)
            return True

    def pop(self) -> Optional[Any]:
        """Next item under DRR, or None when empty."""
        with self._lock:
            # bounded walk: each tenant needs at most ceil(1/(q*w)) visits
            # to accumulate unit deficit; the +4 absorbs empty-queue pops
            for _ in range(4 + 4 * len(self._active) * 4):
                if not self._active:
                    return None
                if self._visiting is None:
                    self._visiting = self._active[0]
                    t = self._visiting
                    self._deficit[t] = self._deficit.get(t, 0.0) \
                        + self.quantum * self.weight(t)
                t = self._visiting
                q = self._queues.get(t)
                if not q:
                    self._active.popleft()
                    self._deficit[t] = 0.0
                    self._visiting = None
                    continue
                if self._deficit[t] >= 1.0:
                    self._deficit[t] -= 1.0
                    item = q.popleft()
                    if not q:
                        self._active.popleft()
                        self._deficit[t] = 0.0
                        self._visiting = None
                    return item
                # budget spent: move this tenant to the back of the cycle
                self._active.rotate(-1)
                self._visiting = None
            return None  # pathological weights; treat as empty this call


@dataclass
class _PendingRequest:
    tenant: str
    method: str
    args: tuple
    kwargs: dict
    future: Future
    arrival_ts: float
    deadline: Optional[float]
    routing_key: Optional[str] = None


class IngressHandle:
    """Admission-controlled front door for one deployment.

    ``submit()`` never blocks on capacity: it either enqueues (returning
    a Future that resolves to the replica's response) or sheds with
    :class:`LoadShedError` when the tenant's bounded queue is full.
    Dispatch order across tenants is DRR-fair; within a tenant, FIFO.
    """

    def __init__(self, deployment_name: str, *,
                 slo: Optional[SLOConfig] = None,
                 max_inflight_per_replica: int = 8,
                 handle: Optional[Any] = None,
                 register: bool = True):
        from ray_tpu.serve import api as serve_api

        self._name = deployment_name
        self.slo = slo or SLOConfig()
        self._handle = handle if handle is not None \
            else serve_api.DeploymentHandle(deployment_name)
        self._per_replica = max(1, int(max_inflight_per_replica))
        self._queue = FairQueue(
            max_depth_per_tenant=self.slo.max_queue_depth,
            weights=self.slo.tenant_weights)
        self._inflight: Dict[Any, _PendingRequest] = {}
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._running = True
        self._stats = {"admitted": 0, "shed": 0, "completed": 0,
                       "failed": 0}
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name=f"serve-ingress-dispatch-{deployment_name}")
        self._completer = threading.Thread(
            target=self._complete_loop, daemon=True,
            name=f"serve-ingress-complete-{deployment_name}")
        self._dispatcher.start()
        self._completer.start()
        if register and (self.slo.ttft_target_s is not None
                         or self.slo.queue_target_s is not None):
            try:
                import ray_tpu

                controller = serve_api._get_controller(create=False)
                ray_tpu.get(controller.register_slo.remote(
                    deployment_name, self.slo.to_dict()), timeout=30)
            except Exception:
                pass  # SLO registration is best-effort observability

    # -- public API -----------------------------------------------------

    def submit(self, *args, tenant: str = "default",
               method: str = "__call__",
               routing_key: Optional[str] = None, **kwargs) -> Future:
        fut: Future = Future()
        now = time.monotonic()
        deadline = (now + self.slo.latency_budget_s
                    if self.slo.latency_budget_s is not None else None)
        req = _PendingRequest(tenant, method, args, kwargs, fut, now,
                              deadline, routing_key)
        with self._lock:
            if not self._running:
                fut.set_exception(RuntimeError("ingress closed"))
                return fut
            if not self._queue.push(tenant, req):
                self._stats["shed"] += 1
                _obs()["shed"].inc(tags={"deployment": self._name,
                                         "reason": "queue_full"})
                fut.set_exception(LoadShedError(
                    f"tenant {tenant!r} queue full "
                    f"({self.slo.max_queue_depth} deep) on {self._name}"))
                return fut
            _obs()["queue_depth"].set(len(self._queue),
                                      tags={"deployment": self._name})
            self._work.notify_all()
        return fut

    def stats(self) -> dict:
        with self._lock:
            return {**self._stats, "queued": len(self._queue),
                    "inflight": len(self._inflight),
                    "tenant_depths": self._queue.depths()}

    def close(self, timeout: float = 5.0):
        with self._lock:
            self._running = False
            self._work.notify_all()
        self._dispatcher.join(timeout)
        self._completer.join(timeout)

    # -- internals ------------------------------------------------------

    def _capacity(self) -> int:
        return max(1, len(self._handle._replicas)) * self._per_replica

    def _dispatch_loop(self):
        import ray_tpu  # noqa: F401  (ensures worker context in thread)

        while True:
            with self._lock:
                while self._running and (
                        len(self._queue) == 0
                        or len(self._inflight) >= self._capacity()):
                    self._work.wait(timeout=0.2)
                    if not self._running:
                        break
                if not self._running and len(self._queue) == 0:
                    return
                req = self._queue.pop()
                _obs()["queue_depth"].set(len(self._queue),
                                          tags={"deployment": self._name})
            if req is None:
                continue
            now = time.monotonic()
            if req.deadline is not None and now > req.deadline:
                # doomed request: its latency budget elapsed in the queue;
                # shedding beats burning replica time on a dead answer
                with self._lock:
                    self._stats["shed"] += 1
                _obs()["shed"].inc(tags={"deployment": self._name,
                                         "reason": "deadline"})
                if not req.future.done():
                    req.future.set_exception(LoadShedError(
                        f"request queued {now - req.arrival_ts:.3f}s, over "
                        f"latency budget {self.slo.latency_budget_s}s"))
                continue
            try:
                h = self._handle if req.method == "__call__" \
                    else self._handle.options(method_name=req.method)
                if req.routing_key is not None:
                    ref = h.remote_with_key(req.routing_key, *req.args,
                                            **req.kwargs)
                else:
                    ref = h.remote(*req.args, **req.kwargs)
            except Exception as e:
                with self._lock:
                    self._stats["failed"] += 1
                if not req.future.done():
                    req.future.set_exception(e)
                continue
            with self._lock:
                self._stats["admitted"] += 1
                self._inflight[ref] = req
            _obs()["admitted"].inc(tags={"deployment": self._name})

    def _complete_loop(self):
        import ray_tpu

        while True:
            with self._lock:
                if not self._running and not self._inflight \
                        and len(self._queue) == 0:
                    return
                refs = list(self._inflight.keys())
            if not refs:
                time.sleep(0.02)
                continue
            try:
                # one vectorized wait across every outstanding ref (rides
                # the core worker's batched result-future setup)
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=0.1)
            except Exception:
                time.sleep(0.1)
                continue
            for ref in ready:
                with self._lock:
                    req = self._inflight.pop(ref, None)
                if req is None:
                    continue
                try:
                    value = ray_tpu.get(ref, timeout=30)
                    with self._lock:
                        self._stats["completed"] += 1
                    if not req.future.done():
                        req.future.set_result(value)
                except Exception as e:
                    with self._lock:
                        self._stats["failed"] += 1
                    if not req.future.done():
                        req.future.set_exception(e)
                with self._lock:
                    self._work.notify_all()


def build_ingress(deployment_name: str, slo: Optional[dict] = None,
                  **kwargs) -> IngressHandle:
    """Convenience constructor taking a plain SLO dict (the HTTP-proxy /
    CLI-facing spelling)."""
    cfg = SLOConfig.from_dict(slo) if isinstance(slo, dict) else slo
    return IngressHandle(deployment_name, slo=cfg, **kwargs)

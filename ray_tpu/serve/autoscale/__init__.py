"""Serve autoscaling control plane (see ray_tpu/serve/README.md).

The closed serving loop: replicas report cumulative request counters ->
:class:`DeploymentMetricsWindow` turns them into sliding-window rates ->
:func:`policy.decide` prices replica demand (Little's law + hysteresis/
cooldown/SLO pressure) -> the serve controller reconciles the target and
emits structured scale events. Ingress admission (:class:`IngressHandle`)
and prefix routing (:class:`PrefixRouter`) complete the loop at the
handle tier.
"""

from ray_tpu.serve.autoscale.ingress import (
    FairQueue,
    IngressHandle,
    LoadShedError,
    SLOConfig,
    build_ingress,
)
from ray_tpu.serve.autoscale.policy import Decision, PolicyState, decide
from ray_tpu.serve.autoscale.router import ConsistentHashRing, PrefixRouter
from ray_tpu.serve.autoscale.window import DeploymentMetricsWindow

__all__ = [
    "DeploymentMetricsWindow",
    "Decision",
    "PolicyState",
    "decide",
    "FairQueue",
    "IngressHandle",
    "LoadShedError",
    "SLOConfig",
    "build_ingress",
    "ConsistentHashRing",
    "PrefixRouter",
]

"""Per-deployment sliding-window rate history for the serve autoscaler.

The GCS :class:`~ray_tpu._private.gcs.MetricsHistory` ring (PR 10) keeps
CLUSTER-WIDE series — it aggregates every process and tag set into one
curve, which is the right view for dashboards but loses the per-deployment
axis the autoscaler must scale on. This module keeps the same
rates-over-a-window idea controller-side: every control tick the
controller polls each replica's cumulative request counters
(``_Replica.take_stats``) and appends ONE cluster-summed sample per
deployment; the window then answers rate questions (request arrival rate,
queue-time p99, execute-time rollups) instead of exposing instantaneous
gauges.

Why rates and not the PR 8 ``take_ongoing_peak()`` gauge: a peak gauge
tells you the burst happened but not how big the demand actually is — 100
requests that arrive and fully drain between two polls read as "peak 3"
if they never overlapped more than 3-deep, yet the *arrival counter*
advanced by 100 and the window prices that as demand. The cumulative
counters make the window burst-proof by construction (the reference's
autoscaling_state.py draws the same conclusion: scale on aggregated
request metrics over a look-back window, not on point samples).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

# one replica's cumulative counter snapshot (take_stats() payload); the
# window consumes the cluster-wide sum so dead replicas just drop out
STAT_KEYS = ("arrived", "completed", "execute_sum", "execute_count")


def percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of an ascending list (None when empty)."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


class DeploymentMetricsWindow:
    """Bounded ring of per-tick cluster-summed replica stats for ONE
    deployment, answering windowed rates.

    Counter deltas are clamped at zero: the cluster value is a sum over
    the CURRENT replica set, so a replica death (or replacement during a
    rolling update) steps the cumulative total down — that step is a
    membership change, not negative traffic (same clamp the GCS rollup
    tier applies to counter rates)."""

    def __init__(self, window_s: float = 30.0, max_points: int = 256,
                 max_queue_samples: int = 512):
        self.window_s = float(window_s)
        self._points: deque = deque(maxlen=max_points)
        # drained per-request queue-wait samples ride separately from the
        # tick ring so p99 comes from real observations, not tick means
        self._queue_samples: deque = deque(maxlen=max_queue_samples)
        # server-side time-to-first-token observations (replica-stamped:
        # handle dispatch -> first response chunk), same drain shape
        self._ttft_samples: deque = deque(maxlen=max_queue_samples)

    # -- ingestion ------------------------------------------------------

    def observe(self, replica_stats: List[dict],
                now: Optional[float] = None) -> dict:
        """Append one sample: the sum of every responding replica's
        cumulative counters plus the instantaneous ongoing/peak levels
        (kept for rollup averaging, never consumed as point gauges).
        Timestamps are ``time.monotonic()`` — pass a consistent clock."""
        now = time.monotonic() if now is None else now
        sample = {"ts": now, "n_replicas": len(replica_stats),
                  "ongoing": 0, "peak": 0}
        for key in STAT_KEYS:
            sample[key] = 0
        for st in replica_stats:
            for key in STAT_KEYS:
                sample[key] += st.get(key, 0) or 0
            sample["ongoing"] += st.get("ongoing", 0) or 0
            sample["peak"] += st.get("peak", 0) or 0
            for q in st.get("queue_samples") or ():
                self._queue_samples.append((now, float(q)))
            for t in st.get("ttft_samples") or ():
                self._ttft_samples.append((now, float(t)))
        self._points.append(sample)
        return sample

    # -- reads ----------------------------------------------------------

    def _window(self, now: Optional[float] = None) -> List[dict]:
        now = time.monotonic() if now is None else now
        lo = now - self.window_s
        return [p for p in self._points if p["ts"] >= lo]

    def _rate(self, key: str, now: Optional[float] = None) -> float:
        pts = self._window(now)
        if len(pts) < 2:
            return 0.0
        span = max(pts[-1]["ts"] - pts[0]["ts"], 1e-9)
        return max(0.0, pts[-1][key] - pts[0][key]) / span

    def arrival_rate(self, now: Optional[float] = None) -> float:
        """Requests/s entering replicas over the window (cumulative
        arrival counter delta — sees bursts that drain between ticks)."""
        return self._rate("arrived", now)

    def completion_rate(self, now: Optional[float] = None) -> float:
        return self._rate("completed", now)

    def execute_mean_s(self, now: Optional[float] = None) -> Optional[float]:
        """Mean user-callable execution seconds over the window (None
        until a request completes inside it)."""
        pts = self._window(now)
        if len(pts) < 2:
            return None
        dn = pts[-1]["execute_count"] - pts[0]["execute_count"]
        ds = pts[-1]["execute_sum"] - pts[0]["execute_sum"]
        if dn <= 0 or ds < 0:
            return None
        return ds / dn

    def queue_p99_s(self, now: Optional[float] = None) -> Optional[float]:
        now = time.monotonic() if now is None else now
        lo = now - self.window_s
        vals = sorted(v for ts, v in self._queue_samples if ts >= lo)
        return percentile(vals, 0.99)

    def ttft_p99_s(self, now: Optional[float] = None) -> Optional[float]:
        """Windowed p99 of replica-stamped time-to-first-token (None
        until a first token lands inside the window)."""
        now = time.monotonic() if now is None else now
        lo = now - self.window_s
        vals = sorted(v for ts, v in self._ttft_samples if ts >= lo)
        return percentile(vals, 0.99)

    def avg_ongoing(self, now: Optional[float] = None) -> float:
        """Mean concurrent-request level across window ticks — a rollup
        of the level series, not a point sample."""
        pts = self._window(now)
        if not pts:
            return 0.0
        return sum(p["ongoing"] for p in pts) / len(pts)

    def peak_ongoing(self, now: Optional[float] = None) -> int:
        pts = self._window(now)
        return max((p["peak"] for p in pts), default=0)

    def rollup(self, now: Optional[float] = None) -> Dict[str, object]:
        """One dict with every windowed rate the policy consumes (also the
        payload published to the ``serve`` KV namespace for /api/serve,
        ``ray-tpu serve`` and the health monitor)."""
        now = time.monotonic() if now is None else now
        return {
            "window_s": self.window_s,
            "arrival_rate": self.arrival_rate(now),
            "completion_rate": self.completion_rate(now),
            "execute_mean_s": self.execute_mean_s(now),
            "queue_p99_s": self.queue_p99_s(now),
            "ttft_p99_s": self.ttft_p99_s(now),
            "avg_ongoing": self.avg_ongoing(now),
            "peak_ongoing": self.peak_ongoing(now),
            "samples": len(self._points),
        }

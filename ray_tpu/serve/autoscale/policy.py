"""Demand-driven autoscaling policy (reference: serve/autoscaling_state.py
+ autoscaling_policy.py, rebuilt on window RATES instead of point gauges).

The decision function prices demand by Little's law: the concurrency a
deployment must absorb is ``arrival_rate x mean_execute_seconds``. Divided
by the per-replica concurrency target that yields a fractional replica
demand; the policy then applies

* **hysteresis** — a replica is only released when demand clears a band
  BELOW the next-lower capacity step, so demand hovering at a boundary
  never flaps the replica count;
* **sustained-condition delays** — up/down pressure must hold for
  ``upscale_delay_s`` / ``downscale_delay_s`` before acting (the
  reference's delay smoothing);
* **cooldown** — after any scale action the policy holds for
  ``scale_cooldown_s`` regardless of pressure, bounding actuation rate
  while replicas start/drain;
* **SLO pressure** — when the deployment registered a queue-wait or
  time-to-first-token target and the windowed p99 exceeds it, the policy
  treats that as up-pressure even if the rate math says capacity is
  sufficient (the rate view can under-price demand while a backlog is
  already queued or streams are slow to first byte).

Scale-up jumps straight to the demanded replica count (bursts need
capacity NOW); scale-down steps one replica at a time so each release
re-prices demand against the smaller set before the next.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.serve.autoscale.window import DeploymentMetricsWindow


@dataclass
class Decision:
    """One autoscale verdict: the replica target to reconcile toward and
    the structured reason that rides the task-plane scale event."""

    want: int
    reason: str
    direction: str  # "up" | "down" | "hold"
    metrics: dict = field(default_factory=dict)


@dataclass
class PolicyState:
    """Per-deployment smoothing state (lives in the controller's app
    record; the policy itself stays stateless/testable)."""

    up_since: Optional[float] = None
    down_since: Optional[float] = None
    last_scale_ts: float = 0.0


def replica_demand(window: DeploymentMetricsWindow,
                   target_ongoing: float,
                   now: Optional[float] = None) -> tuple:
    """Fractional replicas demanded by the window rates. Returns
    ``(demand, detail)`` where detail carries the inputs for the reason
    string / scale event."""
    arrival = window.arrival_rate(now)
    exec_mean = window.execute_mean_s(now)
    avg_ongoing = window.avg_ongoing(now)
    # Little's law concurrency; falls back to the windowed ongoing rollup
    # while no request has completed inside the window yet (cold start /
    # first burst) — both are window aggregates, never point samples
    littles = arrival * exec_mean if exec_mean is not None else 0.0
    concurrency = max(littles, avg_ongoing)
    demand = concurrency / max(target_ongoing, 1e-9)
    return demand, {
        "arrival_rate": round(arrival, 4),
        "execute_mean_s": None if exec_mean is None else round(exec_mean, 6),
        "avg_ongoing": round(avg_ongoing, 4),
        "concurrency_demand": round(concurrency, 4),
        "replica_demand": round(demand, 4),
    }


def decide(window: DeploymentMetricsWindow, *, current_target: int,
           config, state: PolicyState, now: float,
           queue_target_s: Optional[float] = None,
           ttft_target_s: Optional[float] = None) -> Decision:
    """One policy evaluation. ``config`` is the deployment's
    AutoscalingConfig (min/max bounds, target_ongoing_requests, delays,
    hysteresis, cooldown); ``queue_target_s`` / ``ttft_target_s`` the
    registered queue-wait and time-to-first-token SLOs, if any."""
    demand, detail = replica_demand(window, config.target_ongoing_requests,
                                    now)
    detail["current_target"] = current_target
    queue_p99 = window.queue_p99_s(now)
    detail["queue_p99_s"] = None if queue_p99 is None else round(queue_p99, 6)
    ttft_p99 = window.ttft_p99_s(now)
    detail["ttft_p99_s"] = None if ttft_p99 is None else round(ttft_p99, 6)

    queue_pressure = (queue_target_s is not None and queue_p99 is not None
                      and queue_p99 > queue_target_s)
    ttft_pressure = (ttft_target_s is not None and ttft_p99 is not None
                     and ttft_p99 > ttft_target_s)
    slo_pressure = queue_pressure or ttft_pressure
    up_pressure = demand > current_target + 1e-9 or slo_pressure
    # hysteresis band: only shed a replica when demand fits the SMALLER
    # set with headroom to spare
    down_ok = demand < (current_target - 1) * (1.0 - config.hysteresis) \
        + 1e-9
    down_pressure = (not up_pressure and current_target > config.min_replicas
                     and down_ok)

    in_cooldown = now - state.last_scale_ts < config.scale_cooldown_s

    if up_pressure and current_target < config.max_replicas:
        state.down_since = None
        if state.up_since is None:
            state.up_since = now
        if not in_cooldown and now - state.up_since >= config.upscale_delay_s:
            want = min(config.max_replicas,
                       max(current_target + 1, math.ceil(demand)))
            state.up_since = None
            state.last_scale_ts = now
            if slo_pressure and demand <= current_target:
                why = ("queue p99 %.3fs over SLO %.3fs"
                       % (queue_p99, queue_target_s) if queue_pressure
                       else "ttft p99 %.3fs over SLO %.3fs"
                       % (ttft_p99, ttft_target_s))
            else:
                why = "demand %.2f replicas > target %d" % (demand,
                                                            current_target)
            return Decision(want, why, "up", detail)
        return Decision(current_target, "up-pressure pending delay/cooldown",
                        "hold", detail)
    if down_pressure:
        state.up_since = None
        if state.down_since is None:
            state.down_since = now
        if not in_cooldown and now - state.down_since >= \
                config.downscale_delay_s:
            want = max(config.min_replicas, current_target - 1)
            state.down_since = None
            state.last_scale_ts = now
            return Decision(
                want, "demand %.2f replicas under hysteresis band of %d"
                % (demand, current_target), "down", detail)
        return Decision(current_target,
                        "down-pressure pending delay/cooldown", "hold",
                        detail)
    state.up_since = None
    state.down_since = None
    return Decision(current_target, "demand within band", "hold", detail)

"""Model multiplexing (reference: python/ray/serve/multiplex.py +
api.get_multiplexed_model_id): many fine-tuned models share one
deployment's replicas; each replica lazily loads up to N models in an LRU
cache, and the handle routes a given model id consistently to the same
replica so its cache stays hot.

Usage::

    @serve.deployment
    class ModelZoo:
        @serve.multiplexed(max_num_models_per_replica=3)
        async def get_model(self, model_id: str):
            return load_model(model_id)       # called once per id per replica

        async def __call__(self, body):
            model = await self.get_model(serve.get_multiplexed_model_id())
            return model(body)

    handle.options(multiplexed_model_id="m1").remote({...})
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import functools
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id the in-flight request was routed with."""
    return _current_model_id.get()


def _set_current_model_id(model_id: str):
    return _current_model_id.set(model_id)


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for an async model loader ``(self, model_id) -> model``.
    Results are cached per replica in an LRU of the given capacity; evicted
    models are dropped (and their ``__del__`` releases device memory)."""

    def wrap(fn):
        caches: dict = {}
        inflight: dict = {}

        @functools.wraps(fn)
        async def wrapper(self, model_id: str):
            cache = caches.get(id(self))
            if cache is None:
                cache = caches[id(self)] = collections.OrderedDict()
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            # single-flight per (replica, model): concurrent requests for the
            # same id await ONE load instead of loading N copies
            key = (id(self), model_id)
            existing = inflight.get(key)
            if existing is not None:
                return await asyncio.shield(existing)
            fut = asyncio.get_event_loop().create_future()
            inflight[key] = fut
            try:
                model = fn(self, model_id)
                if asyncio.iscoroutine(model):
                    model = await model
                cache[model_id] = model
                cache.move_to_end(model_id)
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
                fut.set_result(model)
                return model
            except BaseException as e:
                if not fut.done():
                    fut.set_exception(e)
                raise
            finally:
                inflight.pop(key, None)

        wrapper.__serve_multiplexed__ = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap

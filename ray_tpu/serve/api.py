"""Serve implementation: deployments, controller, replicas, router, batching.

Reference mapping:
- ``@serve.deployment`` / ``.bind`` / ``serve.run``: serve/api.py:320,681
- ``ServeController``: serve/_private/controller.py:102 (reconciles replica
  sets, restarts dead replicas)
- replica: serve/_private/replica.py (user callable behind an actor)
- router: power-of-two-choices on outstanding requests
  (serve/_private/request_router/pow_2_router.py:27), client-side here
- ``@serve.batch``: serve/batching.py (async dynamic batching)
"""

from __future__ import annotations

import asyncio
import copy
import functools
import random
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.exceptions import TaskError

CONTROLLER_NAME = "serve_controller"


# ---------------------------------------------------------------------------
# public authoring API
# ---------------------------------------------------------------------------


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    ray_actor_options: Dict[str, Any] = field(default_factory=lambda: {"num_cpus": 1.0})
    health_check_period_s: float = 2.0


class Deployment:
    def __init__(self, target, name: str, config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None, num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None) -> "Deployment":
        cfg = copy.deepcopy(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        return Deployment(self._target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


@dataclass
class Application:
    deployment: Deployment
    init_args: tuple
    init_kwargs: dict


def deployment(target=None, *, name: Optional[str] = None, num_replicas: int = 1,
               max_ongoing_requests: int = 16,
               ray_actor_options: Optional[Dict[str, Any]] = None):
    """@serve.deployment on a class or function."""

    def wrap(t):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options or {"num_cpus": 1.0},
        )
        return Deployment(t, name or t.__name__, cfg)

    if target is not None:
        return wrap(target)
    return wrap


# ---------------------------------------------------------------------------
# replica actor
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _Replica:
    def __init__(self, target_blob: bytes, init_args_blob: bytes):
        import cloudpickle as _cp

        target = _cp.loads(target_blob)
        args, kwargs = _cp.loads(init_args_blob)
        # resolve nested Applications into handles (model composition)
        args = tuple(_resolve_app_args(a) for a in args)
        kwargs = {k: _resolve_app_args(v) for k, v in kwargs.items()}
        if isinstance(target, type):
            self._callable = target(*args, **kwargs)
        else:
            self._callable = functools.partial(target, *args, **kwargs) \
                if args or kwargs else target
        self._num_ongoing = 0

    async def handle_request(self, method_name: str, args_blob: bytes):
        import cloudpickle as _cp

        args, kwargs = _cp.loads(args_blob)
        self._num_ongoing += 1
        try:
            if method_name == "__call__":
                if not callable(self._callable):
                    raise TypeError("deployment target is not callable")
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name)
            if asyncio.iscoroutinefunction(fn):
                out = await fn(*args, **kwargs)
            else:
                # sync user code runs off-loop so it can call other handles
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(
                    None, functools.partial(fn, *args, **kwargs))
                if asyncio.iscoroutine(out):
                    out = await out
            return out
        finally:
            self._num_ongoing -= 1

    def num_ongoing(self) -> int:
        return self._num_ongoing

    def health(self) -> bool:
        return True


def _resolve_app_args(v):
    if isinstance(v, Application):
        return get_app_handle(v.deployment.name)
    return v


# ---------------------------------------------------------------------------
# controller actor
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _ServeController:
    """Reconciles target replica sets; restarts dead replicas."""

    def __init__(self):
        self.apps: Dict[str, dict] = {}  # name -> {blob, init, cfg, replicas}
        self._running = True

    def deploy(self, name: str, target_blob: bytes, init_blob: bytes,
               cfg_blob: bytes) -> bool:
        import cloudpickle as _cp

        cfg = _cp.loads(cfg_blob)
        old = self.apps.get(name)
        if old:
            for r in old["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        self.apps[name] = {"blob": target_blob, "init": init_blob, "cfg": cfg,
                           "replicas": []}
        self._reconcile(name)
        return True

    def _reconcile(self, name: str):
        from ray_tpu.serve import api as _api

        app = self.apps[name]
        cfg = app["cfg"]
        want = cfg.num_replicas
        alive = []
        for r in app["replicas"]:
            try:
                ray_tpu.get(r.health.remote(), timeout=10)
                alive.append(r)
            except Exception:
                pass
        while len(alive) < want:
            opts = dict(cfg.ray_actor_options)
            replica = _api._Replica.options(
                num_cpus=opts.get("num_cpus", 1.0),
                num_tpus=opts.get("num_tpus", 0.0),
                resources=opts.get("resources", {}),
                max_concurrency=cfg.max_ongoing_requests,
                max_restarts=-1,
            ).remote(app["blob"], app["init"])
            alive.append(replica)
        for extra in alive[want:]:
            try:
                ray_tpu.kill(extra)
            except Exception:
                pass
        app["replicas"] = alive[:want]

    def check_replicas(self):
        """Periodic health reconcile (driven by handle/proxy pings)."""
        for name in list(self.apps):
            self._reconcile(name)
        return True

    def get_replicas(self, name: str):
        app = self.apps.get(name)
        if app is None:
            raise KeyError(f"no deployment named {name!r}")
        return list(app["replicas"])

    def delete(self, name: str) -> bool:
        app = self.apps.pop(name, None)
        if app:
            for r in app["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        return True

    def status(self) -> Dict[str, Any]:
        return {
            name: {"num_replicas": len(app["replicas"]),
                   "target": app["cfg"].num_replicas}
            for name, app in self.apps.items()
        }


def _get_controller(create: bool = True):
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        if not create:
            raise
        return _ServeController.options(
            name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1,
            max_concurrency=16, get_if_exists=True).remote()


# ---------------------------------------------------------------------------
# handle + router
# ---------------------------------------------------------------------------


class DeploymentHandle:
    """Client-side router: power-of-two-choices over replica pending counts."""

    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self._name = deployment_name
        self._method = method_name
        self._replicas: List[Any] = []
        self._pending: Dict[Any, int] = {}
        self._last_refresh = 0.0

    def options(self, method_name: str) -> "DeploymentHandle":
        h = DeploymentHandle(self._name, method_name)
        h._replicas = self._replicas
        h._pending = self._pending
        return h

    def _refresh(self, force: bool = False):
        if not force and self._replicas and time.monotonic() - self._last_refresh < 5.0:
            return
        controller = _get_controller(create=False)
        self._replicas = ray_tpu.get(
            controller.get_replicas.remote(self._name), timeout=60)
        self._pending = {r: 0 for r in self._replicas}
        self._last_refresh = time.monotonic()

    def _pick(self):
        self._refresh()
        if not self._replicas:
            raise RuntimeError(f"deployment {self._name} has no replicas")
        if len(self._replicas) == 1:
            return self._replicas[0]
        a, b = random.sample(self._replicas, 2)
        return a if self._pending.get(a, 0) <= self._pending.get(b, 0) else b

    def remote(self, *args, **kwargs):
        replica = self._pick()
        return self._dispatch(replica, args, kwargs)

    def remote_with_key(self, routing_key: str, *args, **kwargs):
        """Consistent routing: the same key prefers the same replica (used by
        prefix-aware LLM routing; falls back to pow-2 with one replica)."""
        import hashlib

        self._refresh()
        if len(self._replicas) > 1:
            digest = hashlib.md5(routing_key.encode()).digest()
            replica = self._replicas[
                int.from_bytes(digest[:4], "little") % len(self._replicas)]
        else:
            replica = self._pick()
        return self._dispatch(replica, args, kwargs)

    def _dispatch(self, replica, args, kwargs):
        # pending counters decay by zeroing at each periodic refresh
        self._pending[replica] = self._pending.get(replica, 0) + 1
        blob = cloudpickle.dumps((args, kwargs))
        return replica.handle_request.remote(self._method, blob)

    def __reduce__(self):
        return (DeploymentHandle, (self._name, self._method))


def get_app_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


# ---------------------------------------------------------------------------
# run / delete / status
# ---------------------------------------------------------------------------


def run(app: Application, name: Optional[str] = None, *,
        _blocking: bool = True) -> DeploymentHandle:
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = _get_controller()
    dep = app.deployment
    deploy_name = name or dep.name
    ray_tpu.get(controller.deploy.remote(
        deploy_name,
        cloudpickle.dumps(dep._target),
        cloudpickle.dumps((app.init_args, app.init_kwargs)),
        cloudpickle.dumps(dep.config)), timeout=600)
    handle = DeploymentHandle(deploy_name)
    handle._refresh(force=True)
    return handle


def delete(name: str):
    controller = _get_controller(create=False)
    ray_tpu.get(controller.delete.remote(name), timeout=60)


def status() -> Dict[str, Any]:
    controller = _get_controller(create=False)
    return ray_tpu.get(controller.status.remote(), timeout=60)


def shutdown():
    try:
        controller = _get_controller(create=False)
    except ValueError:
        return
    for name in list(ray_tpu.get(controller.status.remote(), timeout=60)):
        ray_tpu.get(controller.delete.remote(name), timeout=60)
    ray_tpu.kill(controller)


# ---------------------------------------------------------------------------
# dynamic batching (reference: serve/batching.py)
# ---------------------------------------------------------------------------


def batch(_fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01):
    """Decorator for async methods taking a list of requests: concurrent
    single calls are buffered into one batched invocation."""

    def wrap(fn):
        state = {"queue": [], "event": None, "task": None}

        async def flush(self_ref):
            await asyncio.sleep(batch_wait_timeout_s)
            await do_flush(self_ref)

        async def do_flush(self_ref):
            queue, state["queue"] = state["queue"], []
            state["task"] = None
            if not queue:
                return
            items = [item for item, _ in queue]
            futs = [f for _, f in queue]
            try:
                results = await fn(self_ref, items) if self_ref is not None \
                    else await fn(items)
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                self_ref, item = args
            else:
                self_ref, item = None, args[0]
            fut = asyncio.get_event_loop().create_future()
            state["queue"].append((item, fut))
            if len(state["queue"]) >= max_batch_size:
                if state["task"] is not None:
                    state["task"].cancel()
                    state["task"] = None
                await do_flush(self_ref)
            elif state["task"] is None:
                state["task"] = asyncio.ensure_future(flush(self_ref))
            return await fut

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


# ---------------------------------------------------------------------------
# HTTP proxy (reference: serve/_private/proxy.py)
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _HttpProxy:
    """aiohttp ingress: POST /<deployment> with a JSON body routes to the
    deployment handle and returns the JSON-serialized response."""

    def __init__(self, port: int):
        self.port = port
        self._runner = None

    async def start(self) -> int:
        import json

        from aiohttp import web

        def _route(name, body):
            h = DeploymentHandle(name)
            return ray_tpu.get(h.remote(body), timeout=120)

        async def handle(request):
            name = request.match_info["name"]
            try:
                body = await request.json() if request.can_read_body else {}
            except Exception:
                body = {}
            try:
                # route off-loop: handle calls block on the core worker
                loop = asyncio.get_event_loop()
                result = await loop.run_in_executor(
                    None, functools.partial(_route, name, body))
                return web.json_response({"result": result})
            except Exception as e:
                return web.json_response({"error": str(e)}, status=500)

        app = web.Application()
        app.router.add_post("/{name}", handle)
        app.router.add_get("/-/healthz", lambda r: web.json_response({"ok": True}))
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", self.port)
        await site.start()
        return self.port


def start_http_proxy(port: int = 0) -> int:
    import socket

    if port == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    proxy = _HttpProxy.options(name="serve_http_proxy", lifetime="detached",
                               num_cpus=0.1, max_concurrency=64,
                               get_if_exists=True).remote(port)
    return ray_tpu.get(proxy.start.remote(), timeout=120)

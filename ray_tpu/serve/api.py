"""Serve implementation: deployments, controller, replicas, router, batching.

Reference mapping:
- ``@serve.deployment`` / ``.bind`` / ``serve.run``: serve/api.py:320,681
- ``ServeController``: serve/_private/controller.py:102 (reconciles replica
  sets, restarts dead replicas)
- replica: serve/_private/replica.py (user callable behind an actor)
- router: power-of-two-choices on outstanding requests
  (serve/_private/request_router/pow_2_router.py:27), client-side here
- ``@serve.batch``: serve/batching.py (async dynamic batching)
"""

from __future__ import annotations

import asyncio
import copy
import functools
import random
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.exceptions import TaskError

CONTROLLER_NAME = "serve_controller"

_obs_lock = threading.Lock()
_obs_metrics: Optional[dict] = None


def _obs() -> dict:
    """Lazily-created serve request metrics on the shared registry
    (always on: every request through a handle/replica lands in
    ``/metrics`` with route/queue/execute phase histograms)."""
    global _obs_metrics
    with _obs_lock:
        if _obs_metrics is None:
            from ray_tpu.util.metrics import Counter, Histogram

            bounds = [0.001, 0.01, 0.1, 1, 10]
            _obs_metrics = {
                "route": Histogram(
                    "ray_tpu.serve.route_seconds",
                    "handle-side routing: topology refresh + replica pick",
                    boundaries=bounds),
                "queue": Histogram(
                    "ray_tpu.serve.queue_seconds",
                    "request wait between handle dispatch and replica "
                    "execution start", boundaries=bounds),
                "execute": Histogram(
                    "ray_tpu.serve.execute_seconds",
                    "user-callable execution on the replica",
                    boundaries=bounds),
                "requests": Counter(
                    "ray_tpu.serve.requests",
                    "requests executed by this replica process"),
                "ttft": Histogram(
                    "ray_tpu.serve.ttft_seconds",
                    "server-side time to first token: handle dispatch to "
                    "the replica's first response chunk (whole response "
                    "for unary calls)", boundaries=bounds),
            }
        return _obs_metrics


_auto_obs_metrics: Optional[dict] = None


def _auto_obs() -> dict:
    """Autoscaler gauges on the shared registry (controller process):
    flushed into the GCS metrics-history ring like every other metric, so
    dashboards read scale state as rates over time."""
    global _auto_obs_metrics
    with _obs_lock:
        if _auto_obs_metrics is None:
            from ray_tpu.util.metrics import Gauge

            _auto_obs_metrics = {
                "arrival": Gauge(
                    "ray_tpu.serve.arrival_rate",
                    "windowed request arrival rate per deployment (req/s)"),
                "replicas": Gauge(
                    "ray_tpu.serve.replicas",
                    "live replica count per deployment"),
                "target": Gauge(
                    "ray_tpu.serve.target_replicas",
                    "autoscaler replica target per deployment"),
                "queue_p99": Gauge(
                    "ray_tpu.serve.queue_wait_p99_seconds",
                    "windowed p99 queue wait per deployment"),
                "ttft_p99": Gauge(
                    "ray_tpu.serve.ttft_p99_seconds",
                    "windowed p99 server-side time to first token per "
                    "deployment"),
            }
        return _auto_obs_metrics


# ---------------------------------------------------------------------------
# public authoring API
# ---------------------------------------------------------------------------


@dataclass
class AutoscalingConfig:
    """Reference: serve/autoscaling_policy.py + config.AutoscalingConfig.

    Scaling is demand-driven (``serve/autoscale/``): the controller prices
    replica demand from windowed RATES (arrival rate x mean execute time,
    windowed ongoing rollup, queue-wait p99) — never from an
    instantaneous gauge — then applies the sustained-condition delays,
    the hysteresis band, and the post-action cooldown below."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    # sliding window the rates are computed over
    window_s: float = 10.0
    # a replica is released only when demand clears this band below the
    # next-lower capacity step (anti-flap)
    hysteresis: float = 0.1
    # minimum seconds between any two scale actions
    scale_cooldown_s: float = 1.0

    @classmethod
    def from_dict(cls, d: dict) -> "AutoscalingConfig":
        unknown = set(d) - set(cls.__dataclass_fields__)
        if unknown:
            raise ValueError(f"unknown autoscaling_config keys: {sorted(unknown)}")
        return cls(**d)


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    ray_actor_options: Dict[str, Any] = field(default_factory=lambda: {"num_cpus": 1.0})
    health_check_period_s: float = 2.0
    autoscaling: Optional[AutoscalingConfig] = None
    # per-route SLO targets (ingress.SLOConfig dict): registered with the
    # controller -> published to the GCS health monitor; the autoscaler
    # defends queue_target_s as up-pressure
    slo: Optional[dict] = None


class Deployment:
    def __init__(self, target, name: str, config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None, num_replicas=None,
                max_ongoing_requests: Optional[int] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None,
                autoscaling_config: Optional[dict] = None,
                slo: Optional[dict] = None) -> "Deployment":
        cfg = copy.deepcopy(self.config)
        if num_replicas == "auto" or autoscaling_config is not None:
            if isinstance(num_replicas, int) and num_replicas != 1:
                raise ValueError(
                    "num_replicas and autoscaling_config are mutually "
                    "exclusive; set min/max_replicas in the config instead")
            cfg.autoscaling = AutoscalingConfig.from_dict(autoscaling_config or {})
            cfg.num_replicas = cfg.autoscaling.min_replicas
        elif num_replicas is not None:
            cfg.num_replicas = num_replicas
            cfg.autoscaling = None
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if slo is not None:
            from ray_tpu.serve.autoscale.ingress import SLOConfig

            cfg.slo = SLOConfig.from_dict(slo).to_dict()  # validate keys
        return Deployment(self._target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


@dataclass
class Application:
    deployment: Deployment
    init_args: tuple
    init_kwargs: dict


def deployment(target=None, *, name: Optional[str] = None, num_replicas=1,
               max_ongoing_requests: int = 16,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               autoscaling_config: Optional[dict] = None,
               slo: Optional[dict] = None):
    """@serve.deployment on a class or function. ``num_replicas="auto"`` or
    an ``autoscaling_config`` dict enables demand-driven autoscaling; an
    ``slo`` dict (SLOConfig keys) registers per-route targets with the
    controller and the cluster health monitor."""

    def wrap(t):
        auto = None
        n = num_replicas
        if num_replicas == "auto" or autoscaling_config is not None:
            if isinstance(num_replicas, int) and num_replicas != 1:
                raise ValueError(
                    "num_replicas and autoscaling_config are mutually "
                    "exclusive; set min/max_replicas in the config instead")
            auto = AutoscalingConfig.from_dict(autoscaling_config or {})
            n = auto.min_replicas
        slo_dict = None
        if slo is not None:
            from ray_tpu.serve.autoscale.ingress import SLOConfig

            slo_dict = SLOConfig.from_dict(slo).to_dict()
        cfg = DeploymentConfig(
            num_replicas=n,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options or {"num_cpus": 1.0},
            autoscaling=auto,
            slo=slo_dict,
        )
        return Deployment(t, name or t.__name__, cfg)

    if target is not None:
        return wrap(target)
    return wrap


# ---------------------------------------------------------------------------
# replica actor
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _Replica:
    def __init__(self, target_blob: bytes, init_args_blob: bytes):
        from ray_tpu._private.serialization import loads_trusted

        target = loads_trusted(target_blob)
        args, kwargs = loads_trusted(init_args_blob)
        # resolve nested Applications into handles (model composition)
        args = tuple(_resolve_app_args(a) for a in args)
        kwargs = {k: _resolve_app_args(v) for k, v in kwargs.items()}
        if isinstance(target, type):
            self._callable = target(*args, **kwargs)
        else:
            self._callable = functools.partial(target, *args, **kwargs) \
                if args or kwargs else target
        import threading as _th

        self._num_ongoing = 0
        # high-water mark since the autoscaler's last poll: a short burst
        # that starts AND drains between two 0.5s samples is still load —
        # instantaneous sampling alone is blind to it
        self._peak_ongoing = 0
        # request accounting runs on the replica's event loop, but
        # take_ongoing_peak() is a sync actor method on a pool thread:
        # its read-reset is a two-step RMW, so without a lock a burst
        # peaking between the read and the reset is silently dropped
        self._stats_lock = _th.Lock()
        # cumulative demand counters for the rate-based autoscaler
        # (serve/autoscale/window.py): monotone totals survive any number
        # of missed polls, so a burst that fully drains between two
        # control ticks still registers as arrivals
        self._arrived = 0
        self._completed = 0
        self._execute_sum = 0.0
        self._execute_count = 0
        import collections as _coll

        # recent per-request queue-wait observations, drained by
        # take_stats() into the controller's window for the p99 view
        self._queue_drain = _coll.deque(maxlen=256)
        # replica-stamped time-to-first-token observations (handle
        # dispatch -> first yielded chunk / unary completion), same
        # drain -> window -> ttft_p99 path as the queue waits
        self._ttft_drain = _coll.deque(maxlen=256)

    async def handle_request(self, method_name: str, args_blob: bytes):
        import contextvars as _cv

        from ray_tpu._private.serialization import loads_trusted
        from ray_tpu.serve.multiplex import _set_current_model_id
        from ray_tpu.util import tracing

        args, kwargs = loads_trusted(args_blob)
        model_id = kwargs.pop("_serve_multiplexed_model_id", "")
        submit_ts = kwargs.pop("_serve_submit_ts", None)
        now = time.time()
        queue_wait = None
        if submit_ts is not None and now >= submit_ts:
            # handle-dispatch → execution-start wait (the actor queue):
            # built-in queue phase of every serve request
            queue_wait = now - submit_ts
            _obs()["queue"].observe(queue_wait)
            tracing.record_span("serve.queue", submit_ts, now,
                                category="serve")
        token = _set_current_model_id(model_id)
        with self._stats_lock:
            self._num_ongoing += 1
            self._peak_ongoing = max(self._peak_ongoing, self._num_ongoing)
            self._arrived += 1
            if queue_wait is not None:
                self._queue_drain.append(queue_wait)
        t_exec = time.perf_counter()
        try:
            if method_name == "__call__":
                if not callable(self._callable):
                    raise TypeError("deployment target is not callable")
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name)
            with tracing.profile("serve.execute", category="serve"):
                if asyncio.iscoroutinefunction(fn):
                    out = await fn(*args, **kwargs)
                else:
                    # sync user code runs off-loop so it can call other
                    # handles; copy the context so
                    # get_multiplexed_model_id() works there
                    loop = asyncio.get_event_loop()
                    ctx = _cv.copy_context()
                    out = await loop.run_in_executor(
                        None, functools.partial(ctx.run, fn, *args, **kwargs))
                    if asyncio.iscoroutine(out):
                        out = await out
            # a unary response's first token IS the whole response
            self._record_ttft(submit_ts)
            return out
        finally:
            obs = _obs()
            dt_exec = time.perf_counter() - t_exec
            obs["execute"].observe(dt_exec)
            obs["requests"].inc()
            with self._stats_lock:
                self._num_ongoing -= 1
                self._completed += 1
                self._execute_sum += dt_exec
                self._execute_count += 1

    async def handle_request_streaming(self, method_name: str,
                                       args_blob: bytes):
        """Async-generator entry: yields response chunks as the user
        target produces them. Invoked with num_returns="streaming" so each
        yield streams to the caller immediately (reference:
        serve/_private/replica.py UserCallableWrapper.call_user_generator +
        proxy streaming responses)."""
        import inspect

        from ray_tpu._private.serialization import loads_trusted

        args, kwargs = loads_trusted(args_blob)
        kwargs.pop("_serve_multiplexed_model_id", "")
        submit_ts = kwargs.pop("_serve_submit_ts", None)
        now = time.time()
        queue_wait = None
        if submit_ts is not None and now >= submit_ts:
            from ray_tpu.util import tracing

            queue_wait = now - submit_ts
            _obs()["queue"].observe(queue_wait)
            tracing.record_span("serve.queue", submit_ts, now,
                                category="serve")
        if method_name == "__call__":
            fn = self._callable
        else:
            fn = getattr(self._callable, method_name)
        t_exec = time.perf_counter()
        with self._stats_lock:
            self._num_ongoing += 1
            self._peak_ongoing = max(self._peak_ongoing, self._num_ongoing)
            self._arrived += 1
            if queue_wait is not None:
                self._queue_drain.append(queue_wait)
        stamped = False

        def _stamp():
            # first produced chunk stamps the server-side TTFT; later
            # chunks are throughput, not first-token latency
            nonlocal stamped
            if not stamped:
                stamped = True
                self._record_ttft(submit_ts)

        try:
            if inspect.isasyncgenfunction(fn):
                async for chunk in fn(*args, **kwargs):
                    _stamp()
                    yield chunk
                return
            out = fn(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = await out
            if hasattr(out, "__aiter__"):
                async for chunk in out:
                    _stamp()
                    yield chunk
            elif hasattr(out, "__next__") or (
                    hasattr(out, "__iter__")
                    and not isinstance(out, (str, bytes, dict))):
                for chunk in out:
                    _stamp()
                    yield chunk
            else:
                _stamp()
                yield out
        finally:
            dt_exec = time.perf_counter() - t_exec
            with self._stats_lock:
                self._num_ongoing -= 1
                self._completed += 1
                self._execute_sum += dt_exec
                self._execute_count += 1

    def _record_ttft(self, submit_ts: Optional[float]):
        """Stamp server-side time-to-first-token for one request (handle
        dispatch wall clock -> now); rides the replica histogram and the
        take_stats drain into the autoscaler's windowed ttft_p99."""
        if submit_ts is None:
            return
        ttft = time.time() - submit_ts
        if ttft < 0:
            return  # clock skew between handle and replica hosts
        _obs()["ttft"].observe(ttft)
        with self._stats_lock:
            self._ttft_drain.append(ttft)

    def num_ongoing(self) -> int:
        return self._num_ongoing

    def take_ongoing_peak(self) -> int:
        """Autoscaler sample: the highest concurrent-request count since
        the previous call (reset to the current level). Peak-based
        sampling sees bursts that fully drain between two polls."""
        with self._stats_lock:
            peak = max(self._peak_ongoing, self._num_ongoing)
            self._peak_ongoing = self._num_ongoing
        return peak

    def take_stats(self) -> dict:
        """Autoscaler sample v2: cumulative counters + drained queue-wait
        samples. Counters are CUMULATIVE so the controller's sliding
        window prices rates from deltas — a burst that arrives and fully
        drains between two polls still moves ``arrived``/``completed``
        (the burst-blindness case a point gauge misses)."""
        with self._stats_lock:
            peak = max(self._peak_ongoing, self._num_ongoing)
            self._peak_ongoing = self._num_ongoing
            queue_samples = list(self._queue_drain)
            self._queue_drain.clear()
            ttft_samples = list(self._ttft_drain)
            self._ttft_drain.clear()
            return {
                "arrived": self._arrived,
                "completed": self._completed,
                "execute_sum": self._execute_sum,
                "execute_count": self._execute_count,
                "ongoing": self._num_ongoing,
                "peak": peak,
                "queue_samples": queue_samples,
                "ttft_samples": ttft_samples,
            }

    def drain(self) -> int:
        """Rolling update support: called on a replica that has been
        removed from the topology; returns outstanding request count so
        the controller can kill it only when it reaches zero."""
        return self._num_ongoing

    def health(self) -> bool:
        return True


def _resolve_app_args(v):
    if isinstance(v, Application):
        return get_app_handle(v.deployment.name)
    return v


# ---------------------------------------------------------------------------
# controller actor
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _ServeController:
    """Reconciles target replica sets; restarts dead replicas; runs the
    request-driven autoscaler (reference: _private/controller.py reconcile
    loop + autoscaling_state.py); publishes versioned topology with a
    long-poll wait (reference: _private/long_poll.py)."""

    def __init__(self):
        import threading as _th

        self.apps: Dict[str, dict] = {}  # name -> {blob, init, cfg, replicas,
        #                                           version, target, scale_ts}
        self._running = True
        self._loop_started = False
        self._cv = _th.Condition()
        # serializes deploy/delete vs the control loop's reconcile/autoscale
        # (both run on executor threads)
        self._mutate = _th.RLock()

    def _bump(self, name: str):
        with self._cv:
            app = self.apps.get(name)
            if app is not None:
                app["version"] += 1
            self._cv.notify_all()

    def deploy(self, name: str, target_blob: bytes, init_blob: bytes,
               cfg_blob: bytes) -> bool:
        from ray_tpu._private.serialization import loads_trusted

        cfg = loads_trusted(cfg_blob)
        with self._mutate:
            old = self.apps.get(name)
            if old:
                # versioned ROLLING update (reference: serve/_private/
                # deployment_state.py _check_and_update_replicas): keep the
                # old code version serving; the reconcile loop replaces
                # replicas one at a time, draining each before killing it,
                # so no request is dropped during an upgrade
                old.update({"blob": target_blob, "init": init_blob,
                            "cfg": cfg, "target": cfg.num_replicas})
                old["code_version"] += 1
                old["version"] += 1
                if cfg.slo:
                    old["slo"] = dict(cfg.slo)
                self._reconcile(name)
                return True
            from ray_tpu.serve.autoscale import (DeploymentMetricsWindow,
                                                 PolicyState)

            auto = cfg.autoscaling
            self.apps[name] = {"blob": target_blob, "init": init_blob,
                               "cfg": cfg, "replicas": [], "version": 0,
                               "code_version": 0, "replica_versions": {},
                               "rollout": None,
                               "target": cfg.num_replicas,
                               "scale_up_since": None, "scale_down_since": None,
                               # demand-driven autoscale plane: sliding
                               # rate window fed by replica counter deltas,
                               # policy smoothing state, bounded scale-event
                               # history, per-deployment SLO targets
                               "window": DeploymentMetricsWindow(
                                   window_s=auto.window_s if auto else 10.0),
                               "policy_state": PolicyState(),
                               "transitions": [],
                               "slo": dict(cfg.slo) if cfg.slo else None,
                               "draining": []}
            self._reconcile(name)
        return True

    def register_slo(self, name: str, slo: dict) -> bool:
        """Ingress handles register per-route SLO targets here; the
        autoscaler turns the queue-wait target into up-pressure and the
        GCS health scan reads the published state for violations."""
        with self._mutate:
            app = self.apps.get(name)
            if app is None:
                return False
            app["slo"] = dict(slo)
        return True

    def _reconcile(self, name: str):
        from ray_tpu.serve import api as _api

        import time as _t

        app = self.apps[name]
        cfg = app["cfg"]
        want = app["target"]
        strikes = app.setdefault("strikes", {})
        alive = []
        # batched health checks under ONE deadline: a single wedged replica
        # must not stall the loop 10s per replica per app
        health_refs = [(r, r.health.remote()) for r in app["replicas"]]
        deadline = _t.monotonic() + 10.0
        for r, ref in health_refs:
            try:
                ray_tpu.get(ref, timeout=max(0.5, deadline - _t.monotonic()))
                strikes.pop(r, None)
                alive.append(r)
            except Exception as e:
                from ray_tpu.exceptions import ActorDiedError

                cause = getattr(e, "cause", None)
                dead = isinstance(e, ActorDiedError) or isinstance(
                    cause, ActorDiedError) or "ActorDied" in str(e)
                # a slow health check under load is not death: give a
                # replica several strikes before replacing it (first-request
                # XLA compiles can starve the loop on small hosts)
                from ray_tpu._private.config import RAY_CONFIG as _cfg

                strikes[r] = strikes.get(r, 0) + 1
                if not dead and strikes[r] < _cfg.serve_health_strikes:
                    alive.append(r)
                else:
                    strikes.pop(r, None)
                    try:
                        ray_tpu.kill(r)  # don't leak the struck-out actor
                    except Exception:
                        pass
        changed = len(alive) != len(app["replicas"])
        rv = app.setdefault("replica_versions", {})
        code_version = app.setdefault("code_version", 0)

        def _start_replica():
            opts = dict(cfg.ray_actor_options)
            replica = _api._Replica.options(
                num_cpus=opts.get("num_cpus", 1.0),
                num_tpus=opts.get("num_tpus", 0.0),
                resources=opts.get("resources", {}),
                max_concurrency=cfg.max_ongoing_requests,
                max_restarts=-1,
            ).remote(app["blob"], app["init"])
            rv[replica] = code_version
            return replica

        while len(alive) < want:
            alive.append(_start_replica())
            changed = True
        draining = app.setdefault("draining", [])
        for extra in alive[want:]:
            # drain-aware scale-down: the surplus replica leaves the
            # topology NOW but stays alive until idle — handle caches
            # refresh on a ~5s TTL, so an immediate kill would drop
            # requests routed by a stale cache (the autoscale bench's
            # zero-drop criterion)
            changed = True
            rv.pop(extra, None)
            draining.append({
                "replica": extra, "removed_at": _t.monotonic(),
                "deadline": _t.monotonic()
                + getattr(cfg, "graceful_shutdown_timeout_s", 30.0)})
        app["replicas"] = alive[:want]
        keep = {id(app.get("surge_replica")),
                id((app.get("rollout") or {}).get("draining"))}
        for r in list(rv):
            if r not in app["replicas"] and id(r) not in keep:
                rv.pop(r, None)
        if self._advance_scaledown(app):
            changed = True
        if self._advance_rollout(name, app):
            changed = True
        if changed:
            self._bump(name)

    def _advance_scaledown(self, app: dict) -> bool:
        """Kill drained scale-down victims: after a stale-cache grace each
        victim is polled for outstanding requests and killed only at zero
        (hard-capped by the graceful window)."""
        import time as _t

        remaining = []
        for entry in app.get("draining", []):
            replica = entry["replica"]
            now = _t.monotonic()
            done = now >= entry["deadline"]
            if not done and now - entry["removed_at"] >= 6.0:
                try:
                    done = ray_tpu.get(replica.drain.remote(),
                                       timeout=5.0) == 0
                except Exception:
                    done = True  # already dead
            if done:
                try:
                    ray_tpu.kill(replica)
                except Exception:
                    pass
            else:
                remaining.append(entry)
        app["draining"] = remaining
        # killing a drained victim never changes the topology (it already
        # left the replica list when the scale-down was decided)
        return False

    def _advance_rollout(self, name: str, app: dict) -> bool:
        """One rolling-update step per control-loop tick (reference:
        deployment_state.py's max-surge-1 rollout): start ONE new-version
        replica; once it answers health, pull ONE old-version replica out
        of the topology; kill it only when drained (or after the graceful
        window). Returns True if the topology changed."""
        import time as _t

        rv = app["replica_versions"]
        code_version = app["code_version"]
        ro = app.get("rollout")
        changed = False
        if ro is not None:
            # a drain is in flight. The victim left the topology, but handle
            # caches refresh on a ~5s TTL — keep it ALIVE (still serving)
            # for a propagation grace so stale routers hit a live replica,
            # then kill once idle (hard-capped by the graceful window)
            draining = ro["draining"]
            now = _t.monotonic()
            done = now >= ro["deadline"]
            if not done and now - ro["removed_at"] >= 6.0:
                try:
                    done = ray_tpu.get(draining.drain.remote(),
                                       timeout=5.0) == 0
                except Exception:
                    done = True  # already dead
            if done:
                rv.pop(draining, None)
                try:
                    ray_tpu.kill(draining)
                except Exception:
                    pass
                app["rollout"] = None
            return False
        stale = [r for r in app["replicas"] if rv.get(r, 0) != code_version]
        if not stale:
            return False
        # surge one new-version replica, wait for it to answer health
        surge = app.get("surge_replica")
        if surge is None:
            opts = dict(app["cfg"].ray_actor_options)
            from ray_tpu.serve import api as _api

            surge = _api._Replica.options(
                num_cpus=opts.get("num_cpus", 1.0),
                num_tpus=opts.get("num_tpus", 0.0),
                resources=opts.get("resources", {}),
                max_concurrency=app["cfg"].max_ongoing_requests,
                max_restarts=-1,
            ).remote(app["blob"], app["init"])
            app["surge_replica"] = surge
            rv[surge] = code_version
            return False
        try:
            ray_tpu.get(surge.health.remote(), timeout=5.0)
        except Exception:
            return False  # not ready yet; try next tick
        # swap: new replica enters the topology, oldest stale leaves it
        victim = stale[0]
        replicas = [r for r in app["replicas"] if r is not victim] + [surge]
        app["replicas"] = replicas
        app["surge_replica"] = None
        app["rollout"] = {
            "draining": victim, "removed_at": _t.monotonic(),
            "deadline": _t.monotonic()
            + getattr(app["cfg"], "graceful_shutdown_timeout_s", 30.0)}
        return True

    def _autoscale(self, name: str):
        """Demand-driven autoscaling: poll cumulative replica counters,
        fold them into the deployment's sliding rate window, and let the
        policy price replica demand (Little's law concurrency, hysteresis,
        cooldown, queue-SLO pressure). Rates from counter DELTAS replace
        the old ``take_ongoing_peak`` gauge — a burst that arrives and
        fully drains between two 0.5s ticks still moves the cumulative
        ``arrived`` counter, so burst blindness is covered structurally
        instead of patched per-gauge (reference: autoscaling_state.py)."""
        import time as _t

        from ray_tpu.serve.autoscale import decide

        app = self.apps[name]
        auto: AutoscalingConfig = app["cfg"].autoscaling
        if auto is None or not app["replicas"]:
            return
        window = app.get("window")
        state = app.get("policy_state")
        if window is None or state is None:
            return
        # wait-then-get: a wedged or cold replica must not stall the
        # control loop — fold in whichever samples arrived in budget
        refs = [r.take_stats.remote() for r in app["replicas"]]
        try:
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=5.0)
            stats = [ray_tpu.get(ref) for ref in ready]
        except Exception:
            return
        if not stats:
            return
        now = _t.monotonic()
        window.observe(stats, now)
        slo = app.get("slo") or {}
        decision = decide(window, current_target=app["target"], config=auto,
                          state=state, now=now,
                          queue_target_s=slo.get("queue_target_s"),
                          ttft_target_s=slo.get("ttft_target_s"))
        rollup = window.rollup(now)
        self._publish_autoscale(name, app, rollup)
        if decision.want != app["target"]:
            before = app["target"]
            app["target"] = decision.want
            self._record_transition(name, before, decision)

    def _record_transition(self, name: str, before: int, decision):
        """Bounded per-app scale history + structured task-plane event +
        timeline span, so ``ray-tpu health``/``/api/timeline`` show WHY
        each scale action fired."""
        import time as _t

        app = self.apps[name]
        entry = {"ts": _t.time(), "from": before, "to": decision.want,
                 "direction": decision.direction, "reason": decision.reason,
                 "metrics": decision.metrics}
        transitions = app.setdefault("transitions", [])
        transitions.append(entry)
        del transitions[:-64]
        try:
            from ray_tpu.util import events, tracing

            events.record(
                "serve", "INFO",
                "autoscale %s: %d -> %d (%s)" % (name, before, decision.want,
                                                 decision.reason),
                deployment=name, direction=decision.direction,
                **decision.metrics)
            end = _t.time()
            tracing.record_span("serve.autoscale", end - 1e-4, end,
                                category="serve", deployment=name,
                                direction=decision.direction,
                                replicas_from=before,
                                replicas_to=decision.want)
        except Exception:  # observability is best-effort by contract
            pass

    def _publish_autoscale(self, name: str, app: dict, rollup: dict):
        """Per-tick observability fan-out: registry gauges (flushed into
        the GCS metrics-history ring) + a KV ``serve`` namespace mirror
        (dashboard ``/api/serve``, CLI, and the GCS health scan's SLO
        check read it back)."""
        try:
            obs = _auto_obs()
            tags = {"deployment": name}
            obs["arrival"].set(rollup.get("arrival_rate") or 0.0, tags=tags)
            obs["replicas"].set(float(len(app["replicas"])), tags=tags)
            obs["target"].set(float(app["target"]), tags=tags)
            qp99 = rollup.get("queue_p99_s")
            if qp99 is not None:
                obs["queue_p99"].set(qp99, tags=tags)
            tp99 = rollup.get("ttft_p99_s")
            if tp99 is not None:
                obs["ttft_p99"].set(tp99, tags=tags)
        except Exception:
            pass
        try:
            import time as _t

            from ray_tpu._private import wire
            from ray_tpu.experimental.internal_kv import _internal_kv_put

            _internal_kv_put(name.encode(), wire.dumps({
                "ts": _t.time(),
                "target": app["target"],
                "replicas": len(app["replicas"]),
                "draining": len(app.get("draining", [])),
                "slo": app.get("slo"),
                "rollup": rollup,
                "transitions": list(app.get("transitions", []))[-8:],
            }), namespace="serve")
        except Exception:  # stats mirror is best-effort by contract
            pass

    def run_control_loop(self):
        """Blocking reconcile+autoscale loop; started once by serve.run
        (runs on one of the controller's executor threads)."""
        import time as _t

        if self._loop_started:
            return False
        self._loop_started = True
        while self._running:
            for name in list(self.apps):
                try:
                    with self._mutate:
                        if name in self.apps:
                            self._autoscale(name)
                            self._reconcile(name)
                except Exception:
                    pass
            _t.sleep(0.5)
        return True

    def check_replicas(self):
        """One reconcile pass (also available to tests/handles)."""
        for name in list(self.apps):
            with self._mutate:
                if name in self.apps:
                    self._reconcile(name)
        return True

    def get_replicas(self, name: str):
        app = self.apps.get(name)
        if app is None:
            raise KeyError(f"no deployment named {name!r}")
        return list(app["replicas"])

    def get_topology(self, name: str):
        """Versioned replica set for handle caches."""
        app = self.apps.get(name)
        if app is None:
            raise KeyError(f"no deployment named {name!r}")
        return {"version": app["version"], "replicas": list(app["replicas"])}

    async def poll_topology(self, name: str, version: int, timeout: float = 25.0):
        """Long-poll: returns when the replica set version moves past
        ``version`` (or on timeout, with the current state). Async so a
        waiting poller costs no executor thread (reference:
        serve/_private/long_poll.py LongPollHost). 100ms check granularity.
        """
        import time as _t

        deadline = _t.monotonic() + timeout
        while True:
            app = self.apps.get(name)
            if app is None:
                return {"version": -1, "replicas": []}
            if app["version"] != version or _t.monotonic() >= deadline:
                return {"version": app["version"],
                        "replicas": list(app["replicas"])}
            await asyncio.sleep(0.1)

    def get_autoscale_state(self, name: str) -> dict:
        """Rate rollup + scale history for one deployment (CLI/dashboard/
        bench read-back)."""
        with self._mutate:
            app = self.apps.get(name)
            if app is None:
                raise KeyError(f"no deployment named {name!r}")
            window = app.get("window")
            return {
                "target": app["target"],
                "replicas": len(app["replicas"]),
                "draining": len(app.get("draining", [])),
                "slo": app.get("slo"),
                "rollup": window.rollup() if window is not None else None,
                "transitions": list(app.get("transitions", [])),
            }

    def delete(self, name: str) -> bool:
        with self._mutate:
            app = self.apps.pop(name, None)
            if app:
                victims = list(app["replicas"]) + [
                    e["replica"] for e in app.get("draining", [])]
                for r in victims:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
                try:
                    from ray_tpu.experimental.internal_kv import \
                        _internal_kv_del

                    _internal_kv_del(name.encode(), namespace="serve")
                except Exception:
                    pass
        with self._cv:
            self._cv.notify_all()
        return True

    def stop_loops(self):
        self._running = False
        return True

    def status(self) -> Dict[str, Any]:
        out = {}
        for name, app in self.apps.items():
            transitions = app.get("transitions") or []
            out[name] = {
                "num_replicas": len(app["replicas"]),
                "target": app["target"],
                "version": app["version"],
                "autoscaling": app["cfg"].autoscaling is not None,
                "draining": len(app.get("draining", [])),
                "slo": app.get("slo"),
                "last_transition": transitions[-1] if transitions else None,
            }
        return out


def _get_controller(create: bool = True):
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        if not create:
            raise
        return _ServeController.options(
            name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1,
            max_concurrency=16, get_if_exists=True).remote()


# ---------------------------------------------------------------------------
# handle + router
# ---------------------------------------------------------------------------


class DeploymentHandle:
    """Client-side router: power-of-two-choices over replica pending counts,
    fed by the controller's versioned topology (long-pollable).
    ``options(routing_policy="prefix")`` swaps keyed routing onto the
    shared consistent-hash :class:`~ray_tpu.serve.autoscale.PrefixRouter`
    policy (promoted from the LLMHandle one-off)."""

    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False,
                 routing_policy: str = "pow2"):
        self._name = deployment_name
        self._method = method_name
        self._model_id = multiplexed_model_id
        self._stream = stream
        self._routing_policy = routing_policy
        self._prefix_router = None
        self._replicas: List[Any] = []
        self._version = -1
        self._pending: Dict[Any, int] = {}
        self._last_refresh = 0.0

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                routing_policy: Optional[str] = None) -> "DeploymentHandle":
        if routing_policy is not None and routing_policy not in (
                "pow2", "prefix"):
            raise ValueError(
                f"unknown routing_policy {routing_policy!r}; "
                "expected 'pow2' or 'prefix'")
        h = DeploymentHandle(
            self._name,
            method_name if method_name is not None else self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._model_id,
            stream if stream is not None else self._stream,
            routing_policy if routing_policy is not None
            else self._routing_policy)
        h._replicas = self._replicas
        h._version = self._version
        h._pending = self._pending
        return h

    def _router(self):
        if self._prefix_router is None:
            from ray_tpu.serve.autoscale import PrefixRouter

            self._prefix_router = PrefixRouter(self._name)
        return self._prefix_router

    def _refresh(self, force: bool = False):
        if not force and self._replicas and time.monotonic() - self._last_refresh < 5.0:
            return
        controller = _get_controller(create=False)
        topo = ray_tpu.get(
            controller.get_topology.remote(self._name), timeout=60)
        self._replicas = topo["replicas"]
        self._version = topo["version"]
        self._pending = {r: 0 for r in self._replicas}
        self._last_refresh = time.monotonic()

    def _long_poll_refresh(self, timeout: float = 25.0):
        """Blocking topology watch (proxies use this in a background
        thread); returns True if the replica set changed."""
        controller = _get_controller(create=False)
        topo = ray_tpu.get(controller.poll_topology.remote(
            self._name, self._version, timeout), timeout=timeout + 30)
        changed = topo["version"] != self._version
        self._replicas = topo["replicas"]
        self._version = topo["version"]
        if changed:
            self._pending = {r: 0 for r in self._replicas}
        self._last_refresh = time.monotonic()
        return changed

    def _pick(self):
        self._refresh()
        if not self._replicas:
            # replicas may be mid-restart: re-ask the controller (it
            # reconciles on demand) before giving up
            deadline = time.monotonic() + 30.0
            while not self._replicas and time.monotonic() < deadline:
                time.sleep(0.2)
                try:
                    self._refresh(force=True)
                except Exception:
                    pass
            if not self._replicas:
                raise RuntimeError(f"deployment {self._name} has no replicas")
        if len(self._replicas) == 1:
            return self._replicas[0]
        a, b = random.sample(self._replicas, 2)
        return a if self._pending.get(a, 0) <= self._pending.get(b, 0) else b

    def remote(self, *args, **kwargs):
        if self._model_id:
            # model multiplexing: the same model id sticks to the same
            # replica so its model cache stays hot (reference:
            # serve/multiplex.py + prefix-aware routing)
            kwargs["_serve_multiplexed_model_id"] = self._model_id
            return self.remote_with_key(self._model_id, *args, **kwargs)
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        with tracing.profile("serve.route", category="serve",
                             deployment=self._name):
            key = None
            if self._routing_policy == "prefix" and args:
                # derive the routing key from the request body's prompt
                # prefix; non-prompt bodies fall back to pow-2
                key = self._router().key_of(args[0])
            replica = self._pick_keyed(key) if key else self._pick()
        _obs()["route"].observe(time.perf_counter() - t0)
        return self._dispatch(replica, args, kwargs)

    def remote_with_key(self, routing_key: str, *args, **kwargs):
        """Consistent routing: the same key prefers the same replica (the
        prefix-cache-aware policy — see autoscale/router.py). A replica
        joining or leaving remaps only ~1/N of the key space, so warm KV
        prefixes survive autoscaling and rolling updates."""
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        with tracing.profile("serve.route", category="serve",
                             deployment=self._name):
            replica = self._pick_keyed(routing_key)
        _obs()["route"].observe(time.perf_counter() - t0)
        return self._dispatch(replica, args, kwargs)

    def _pick_keyed(self, routing_key: str):
        self._refresh()
        if not self._replicas or len(self._replicas) == 1:
            return self._pick()  # waits for replicas / raises
        return self._router().pick(routing_key, self._replicas,
                                   version=self._version)

    def broadcast(self, method_name: str, *args, timeout: float = 120.0,
                  **kwargs) -> List[Any]:
        """Invoke ``method_name`` once on EVERY current replica (bypasses
        routing). This is the live weight-update primitive: replicas keep
        serving while each applies the call — e.g.
        ``handle.broadcast("update_weights", store_name)`` makes every
        replica pull the newest version from a WeightStore with zero
        dropped requests (the method runs as one more actor task on the
        replica's queue; nothing restarts). Returns one result per replica.
        """
        self._refresh(force=True)
        if not self._replicas:
            raise RuntimeError(f"deployment {self._name} has no replicas")
        blob = cloudpickle.dumps((args, kwargs))
        refs = [r.handle_request.remote(method_name, blob)
                for r in self._replicas]
        return ray_tpu.get(refs, timeout=timeout)

    def _dispatch(self, replica, args, kwargs):
        # pending counters decay by zeroing at each periodic refresh
        self._pending[replica] = self._pending.get(replica, 0) + 1
        # dispatch timestamp rides the request so the replica can record
        # the built-in serve.queue span (popped before user code sees it)
        kwargs = {**kwargs, "_serve_submit_ts": time.time()}
        blob = cloudpickle.dumps((args, kwargs))
        if self._stream:
            # ObjectRefGenerator of chunk refs, produced as the replica
            # yields (reference: handle.options(stream=True))
            return replica.handle_request_streaming.options(
                num_returns="streaming").remote(self._method, blob)
        return replica.handle_request.remote(self._method, blob)

    def __reduce__(self):
        return (DeploymentHandle,
                (self._name, self._method, self._model_id, self._stream,
                 self._routing_policy))


def get_app_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


# ---------------------------------------------------------------------------
# run / delete / status
# ---------------------------------------------------------------------------


def run(app: Application, name: Optional[str] = None, *,
        _blocking: bool = True) -> DeploymentHandle:
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = _get_controller()
    dep = app.deployment
    deploy_name = name or dep.name
    ray_tpu.get(controller.deploy.remote(
        deploy_name,
        cloudpickle.dumps(dep._target),
        cloudpickle.dumps((app.init_args, app.init_kwargs)),
        cloudpickle.dumps(dep.config)), timeout=600)
    from ray_tpu._private.worker import global_worker

    if global_worker().mode != "local":
        # local mode executes actor calls inline, so the blocking control
        # loop must not start there (health/autoscaling don't apply anyway)
        controller.run_control_loop.remote()  # idempotent; fire-and-forget
    handle = DeploymentHandle(deploy_name)
    handle._refresh(force=True)
    return handle


def delete(name: str):
    controller = _get_controller(create=False)
    ray_tpu.get(controller.delete.remote(name), timeout=60)


def status() -> Dict[str, Any]:
    controller = _get_controller(create=False)
    return ray_tpu.get(controller.status.remote(), timeout=60)


def shutdown():
    try:
        controller = _get_controller(create=False)
    except ValueError:
        return
    for name in list(ray_tpu.get(controller.status.remote(), timeout=60)):
        ray_tpu.get(controller.delete.remote(name), timeout=60)
    ray_tpu.kill(controller)


# ---------------------------------------------------------------------------
# dynamic batching (reference: serve/batching.py)
# ---------------------------------------------------------------------------


def batch(_fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01):
    """Decorator for async methods taking a list of requests: concurrent
    single calls are buffered into one batched invocation."""

    def wrap(fn):
        state = {"queue": [], "event": None, "task": None}

        async def flush(self_ref):
            await asyncio.sleep(batch_wait_timeout_s)
            await do_flush(self_ref)

        async def do_flush(self_ref):
            queue, state["queue"] = state["queue"], []
            state["task"] = None
            if not queue:
                return
            items = [item for item, _ in queue]
            futs = [f for _, f in queue]
            try:
                results = await fn(self_ref, items) if self_ref is not None \
                    else await fn(items)
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                self_ref, item = args
            else:
                self_ref, item = None, args[0]
            fut = asyncio.get_event_loop().create_future()
            state["queue"].append((item, fut))
            if len(state["queue"]) >= max_batch_size:
                if state["task"] is not None:
                    state["task"].cancel()
                    state["task"] = None
                await do_flush(self_ref)
            elif state["task"] is None:
                state["task"] = asyncio.ensure_future(flush(self_ref))
            return await fut

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


# ---------------------------------------------------------------------------
# HTTP proxy (reference: serve/_private/proxy.py)
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _HttpProxy:
    """aiohttp ingress: POST /<deployment> with a JSON body routes to the
    deployment handle and returns the JSON-serialized response."""

    def __init__(self, port: int):
        self.port = port
        self._runner = None

    async def start(self) -> int:
        import json

        from aiohttp import web

        def _route(name, body):
            h = DeploymentHandle(name)
            return ray_tpu.get(h.remote(body), timeout=120)

        def _encode_chunk(chunk) -> bytes:
            if isinstance(chunk, bytes):
                return chunk
            if isinstance(chunk, str):
                return chunk.encode()
            return (json.dumps(chunk) + "\n").encode()

        async def handle(request):
            name = request.match_info["name"]
            try:
                body = await request.json() if request.can_read_body else {}
            except Exception:
                body = {}
            stream = request.query.get("stream") in ("1", "true") or \
                "text/event-stream" in request.headers.get("Accept", "")
            loop = asyncio.get_event_loop()
            if stream:
                # chunked response: each replica yield is flushed to the
                # client as it arrives (reference: proxy.py streaming
                # responses for generator deployments). A thread-safe
                # queue + stop flag, with every block bounded, so a client
                # disconnect can never strand the pump thread
                import queue as _qmod
                import threading as _th

                q: _qmod.Queue = _qmod.Queue(maxsize=8)
                # raylint: disable=ASY002 cross-thread stop flag: loop side only set()/is_set(), never wait()
                stop = _th.Event()
                _END = object()

                def _put(item) -> bool:
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.5)
                            return True
                        except _qmod.Full:
                            continue
                    return False

                def _pump():
                    try:
                        h = DeploymentHandle(name, stream=True)
                        for ref in h.remote(body):
                            if not _put(ray_tpu.get(ref, timeout=120)):
                                return  # client left; drop the stream
                        _put(_END)
                    except Exception as e:
                        _put(RuntimeError(str(e)))

                resp = web.StreamResponse(
                    headers={"Content-Type": "application/octet-stream",
                             "Transfer-Encoding": "chunked"})
                await resp.prepare(request)
                loop.run_in_executor(None, _pump)
                try:
                    while True:
                        try:
                            item = await loop.run_in_executor(
                                None, functools.partial(q.get, timeout=0.5))
                        except _qmod.Empty:
                            continue
                        if item is _END:
                            break
                        if isinstance(item, RuntimeError):
                            await resp.write(_encode_chunk(
                                {"error": str(item)}))
                            break
                        await resp.write(_encode_chunk(item))
                    await resp.write_eof()
                finally:
                    stop.set()
                return resp
            try:
                # route off-loop: handle calls block on the core worker
                result = await loop.run_in_executor(
                    None, functools.partial(_route, name, body))
                return web.json_response({"result": result})
            except Exception as e:
                return web.json_response({"error": str(e)}, status=500)

        app = web.Application()
        app.router.add_post("/{name}", handle)
        app.router.add_get("/-/healthz", lambda r: web.json_response({"ok": True}))
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", self.port)
        await site.start()
        return self.port


def start_http_proxy(port: int = 0) -> int:
    import socket

    if port == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    proxy = _HttpProxy.options(name="serve_http_proxy", lifetime="detached",
                               num_cpus=0.1, max_concurrency=64,
                               get_if_exists=True).remote(port)
    return ray_tpu.get(proxy.start.remote(), timeout=120)

"""Serve implementation: deployments, controller, replicas, router, batching.

Reference mapping:
- ``@serve.deployment`` / ``.bind`` / ``serve.run``: serve/api.py:320,681
- ``ServeController``: serve/_private/controller.py:102 (reconciles replica
  sets, restarts dead replicas)
- replica: serve/_private/replica.py (user callable behind an actor)
- router: power-of-two-choices on outstanding requests
  (serve/_private/request_router/pow_2_router.py:27), client-side here
- ``@serve.batch``: serve/batching.py (async dynamic batching)
"""

from __future__ import annotations

import asyncio
import copy
import functools
import random
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.exceptions import TaskError

CONTROLLER_NAME = "serve_controller"

_obs_lock = threading.Lock()
_obs_metrics: Optional[dict] = None


def _obs() -> dict:
    """Lazily-created serve request metrics on the shared registry
    (always on: every request through a handle/replica lands in
    ``/metrics`` with route/queue/execute phase histograms)."""
    global _obs_metrics
    with _obs_lock:
        if _obs_metrics is None:
            from ray_tpu.util.metrics import Counter, Histogram

            bounds = [0.001, 0.01, 0.1, 1, 10]
            _obs_metrics = {
                "route": Histogram(
                    "ray_tpu.serve.route_seconds",
                    "handle-side routing: topology refresh + replica pick",
                    boundaries=bounds),
                "queue": Histogram(
                    "ray_tpu.serve.queue_seconds",
                    "request wait between handle dispatch and replica "
                    "execution start", boundaries=bounds),
                "execute": Histogram(
                    "ray_tpu.serve.execute_seconds",
                    "user-callable execution on the replica",
                    boundaries=bounds),
                "requests": Counter(
                    "ray_tpu.serve.requests",
                    "requests executed by this replica process"),
            }
        return _obs_metrics


# ---------------------------------------------------------------------------
# public authoring API
# ---------------------------------------------------------------------------


@dataclass
class AutoscalingConfig:
    """Reference: serve/autoscaling_policy.py + config.AutoscalingConfig."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0

    @classmethod
    def from_dict(cls, d: dict) -> "AutoscalingConfig":
        unknown = set(d) - set(cls.__dataclass_fields__)
        if unknown:
            raise ValueError(f"unknown autoscaling_config keys: {sorted(unknown)}")
        return cls(**d)


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    ray_actor_options: Dict[str, Any] = field(default_factory=lambda: {"num_cpus": 1.0})
    health_check_period_s: float = 2.0
    autoscaling: Optional[AutoscalingConfig] = None


class Deployment:
    def __init__(self, target, name: str, config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None, num_replicas=None,
                max_ongoing_requests: Optional[int] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None,
                autoscaling_config: Optional[dict] = None) -> "Deployment":
        cfg = copy.deepcopy(self.config)
        if num_replicas == "auto" or autoscaling_config is not None:
            if isinstance(num_replicas, int) and num_replicas != 1:
                raise ValueError(
                    "num_replicas and autoscaling_config are mutually "
                    "exclusive; set min/max_replicas in the config instead")
            cfg.autoscaling = AutoscalingConfig.from_dict(autoscaling_config or {})
            cfg.num_replicas = cfg.autoscaling.min_replicas
        elif num_replicas is not None:
            cfg.num_replicas = num_replicas
            cfg.autoscaling = None
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        return Deployment(self._target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


@dataclass
class Application:
    deployment: Deployment
    init_args: tuple
    init_kwargs: dict


def deployment(target=None, *, name: Optional[str] = None, num_replicas=1,
               max_ongoing_requests: int = 16,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               autoscaling_config: Optional[dict] = None):
    """@serve.deployment on a class or function. ``num_replicas="auto"`` or
    an ``autoscaling_config`` dict enables request-driven autoscaling."""

    def wrap(t):
        auto = None
        n = num_replicas
        if num_replicas == "auto" or autoscaling_config is not None:
            if isinstance(num_replicas, int) and num_replicas != 1:
                raise ValueError(
                    "num_replicas and autoscaling_config are mutually "
                    "exclusive; set min/max_replicas in the config instead")
            auto = AutoscalingConfig.from_dict(autoscaling_config or {})
            n = auto.min_replicas
        cfg = DeploymentConfig(
            num_replicas=n,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options or {"num_cpus": 1.0},
            autoscaling=auto,
        )
        return Deployment(t, name or t.__name__, cfg)

    if target is not None:
        return wrap(target)
    return wrap


# ---------------------------------------------------------------------------
# replica actor
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _Replica:
    def __init__(self, target_blob: bytes, init_args_blob: bytes):
        from ray_tpu._private.serialization import loads_trusted

        target = loads_trusted(target_blob)
        args, kwargs = loads_trusted(init_args_blob)
        # resolve nested Applications into handles (model composition)
        args = tuple(_resolve_app_args(a) for a in args)
        kwargs = {k: _resolve_app_args(v) for k, v in kwargs.items()}
        if isinstance(target, type):
            self._callable = target(*args, **kwargs)
        else:
            self._callable = functools.partial(target, *args, **kwargs) \
                if args or kwargs else target
        import threading as _th

        self._num_ongoing = 0
        # high-water mark since the autoscaler's last poll: a short burst
        # that starts AND drains between two 0.5s samples is still load —
        # instantaneous sampling alone is blind to it
        self._peak_ongoing = 0
        # request accounting runs on the replica's event loop, but
        # take_ongoing_peak() is a sync actor method on a pool thread:
        # its read-reset is a two-step RMW, so without a lock a burst
        # peaking between the read and the reset is silently dropped
        self._stats_lock = _th.Lock()

    async def handle_request(self, method_name: str, args_blob: bytes):
        import contextvars as _cv

        from ray_tpu._private.serialization import loads_trusted
        from ray_tpu.serve.multiplex import _set_current_model_id
        from ray_tpu.util import tracing

        args, kwargs = loads_trusted(args_blob)
        model_id = kwargs.pop("_serve_multiplexed_model_id", "")
        submit_ts = kwargs.pop("_serve_submit_ts", None)
        now = time.time()
        if submit_ts is not None and now >= submit_ts:
            # handle-dispatch → execution-start wait (the actor queue):
            # built-in queue phase of every serve request
            _obs()["queue"].observe(now - submit_ts)
            tracing.record_span("serve.queue", submit_ts, now,
                                category="serve")
        token = _set_current_model_id(model_id)
        with self._stats_lock:
            self._num_ongoing += 1
            self._peak_ongoing = max(self._peak_ongoing, self._num_ongoing)
        t_exec = time.perf_counter()
        try:
            if method_name == "__call__":
                if not callable(self._callable):
                    raise TypeError("deployment target is not callable")
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name)
            with tracing.profile("serve.execute", category="serve"):
                if asyncio.iscoroutinefunction(fn):
                    out = await fn(*args, **kwargs)
                else:
                    # sync user code runs off-loop so it can call other
                    # handles; copy the context so
                    # get_multiplexed_model_id() works there
                    loop = asyncio.get_event_loop()
                    ctx = _cv.copy_context()
                    out = await loop.run_in_executor(
                        None, functools.partial(ctx.run, fn, *args, **kwargs))
                    if asyncio.iscoroutine(out):
                        out = await out
            return out
        finally:
            obs = _obs()
            obs["execute"].observe(time.perf_counter() - t_exec)
            obs["requests"].inc()
            with self._stats_lock:
                self._num_ongoing -= 1

    async def handle_request_streaming(self, method_name: str,
                                       args_blob: bytes):
        """Async-generator entry: yields response chunks as the user
        target produces them. Invoked with num_returns="streaming" so each
        yield streams to the caller immediately (reference:
        serve/_private/replica.py UserCallableWrapper.call_user_generator +
        proxy streaming responses)."""
        import inspect

        from ray_tpu._private.serialization import loads_trusted

        args, kwargs = loads_trusted(args_blob)
        kwargs.pop("_serve_multiplexed_model_id", "")
        submit_ts = kwargs.pop("_serve_submit_ts", None)
        now = time.time()
        if submit_ts is not None and now >= submit_ts:
            from ray_tpu.util import tracing

            _obs()["queue"].observe(now - submit_ts)
            tracing.record_span("serve.queue", submit_ts, now,
                                category="serve")
        if method_name == "__call__":
            fn = self._callable
        else:
            fn = getattr(self._callable, method_name)
        with self._stats_lock:
            self._num_ongoing += 1
            self._peak_ongoing = max(self._peak_ongoing, self._num_ongoing)
        try:
            if inspect.isasyncgenfunction(fn):
                async for chunk in fn(*args, **kwargs):
                    yield chunk
                return
            out = fn(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = await out
            if hasattr(out, "__aiter__"):
                async for chunk in out:
                    yield chunk
            elif hasattr(out, "__next__") or (
                    hasattr(out, "__iter__")
                    and not isinstance(out, (str, bytes, dict))):
                for chunk in out:
                    yield chunk
            else:
                yield out
        finally:
            with self._stats_lock:
                self._num_ongoing -= 1

    def num_ongoing(self) -> int:
        return self._num_ongoing

    def take_ongoing_peak(self) -> int:
        """Autoscaler sample: the highest concurrent-request count since
        the previous call (reset to the current level). Peak-based
        sampling sees bursts that fully drain between two polls."""
        with self._stats_lock:
            peak = max(self._peak_ongoing, self._num_ongoing)
            self._peak_ongoing = self._num_ongoing
        return peak

    def drain(self) -> int:
        """Rolling update support: called on a replica that has been
        removed from the topology; returns outstanding request count so
        the controller can kill it only when it reaches zero."""
        return self._num_ongoing

    def health(self) -> bool:
        return True


def _resolve_app_args(v):
    if isinstance(v, Application):
        return get_app_handle(v.deployment.name)
    return v


# ---------------------------------------------------------------------------
# controller actor
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _ServeController:
    """Reconciles target replica sets; restarts dead replicas; runs the
    request-driven autoscaler (reference: _private/controller.py reconcile
    loop + autoscaling_state.py); publishes versioned topology with a
    long-poll wait (reference: _private/long_poll.py)."""

    def __init__(self):
        import threading as _th

        self.apps: Dict[str, dict] = {}  # name -> {blob, init, cfg, replicas,
        #                                           version, target, scale_ts}
        self._running = True
        self._loop_started = False
        self._cv = _th.Condition()
        # serializes deploy/delete vs the control loop's reconcile/autoscale
        # (both run on executor threads)
        self._mutate = _th.RLock()

    def _bump(self, name: str):
        with self._cv:
            app = self.apps.get(name)
            if app is not None:
                app["version"] += 1
            self._cv.notify_all()

    def deploy(self, name: str, target_blob: bytes, init_blob: bytes,
               cfg_blob: bytes) -> bool:
        from ray_tpu._private.serialization import loads_trusted

        cfg = loads_trusted(cfg_blob)
        with self._mutate:
            old = self.apps.get(name)
            if old:
                # versioned ROLLING update (reference: serve/_private/
                # deployment_state.py _check_and_update_replicas): keep the
                # old code version serving; the reconcile loop replaces
                # replicas one at a time, draining each before killing it,
                # so no request is dropped during an upgrade
                old.update({"blob": target_blob, "init": init_blob,
                            "cfg": cfg, "target": cfg.num_replicas})
                old["code_version"] += 1
                old["version"] += 1
                self._reconcile(name)
                return True
            self.apps[name] = {"blob": target_blob, "init": init_blob,
                               "cfg": cfg, "replicas": [], "version": 0,
                               "code_version": 0, "replica_versions": {},
                               "rollout": None,
                               "target": cfg.num_replicas,
                               "scale_up_since": None, "scale_down_since": None}
            self._reconcile(name)
        return True

    def _reconcile(self, name: str):
        from ray_tpu.serve import api as _api

        import time as _t

        app = self.apps[name]
        cfg = app["cfg"]
        want = app["target"]
        strikes = app.setdefault("strikes", {})
        alive = []
        # batched health checks under ONE deadline: a single wedged replica
        # must not stall the loop 10s per replica per app
        health_refs = [(r, r.health.remote()) for r in app["replicas"]]
        deadline = _t.monotonic() + 10.0
        for r, ref in health_refs:
            try:
                ray_tpu.get(ref, timeout=max(0.5, deadline - _t.monotonic()))
                strikes.pop(r, None)
                alive.append(r)
            except Exception as e:
                from ray_tpu.exceptions import ActorDiedError

                cause = getattr(e, "cause", None)
                dead = isinstance(e, ActorDiedError) or isinstance(
                    cause, ActorDiedError) or "ActorDied" in str(e)
                # a slow health check under load is not death: give a
                # replica several strikes before replacing it (first-request
                # XLA compiles can starve the loop on small hosts)
                from ray_tpu._private.config import RAY_CONFIG as _cfg

                strikes[r] = strikes.get(r, 0) + 1
                if not dead and strikes[r] < _cfg.serve_health_strikes:
                    alive.append(r)
                else:
                    strikes.pop(r, None)
                    try:
                        ray_tpu.kill(r)  # don't leak the struck-out actor
                    except Exception:
                        pass
        changed = len(alive) != len(app["replicas"])
        rv = app.setdefault("replica_versions", {})
        code_version = app.setdefault("code_version", 0)

        def _start_replica():
            opts = dict(cfg.ray_actor_options)
            replica = _api._Replica.options(
                num_cpus=opts.get("num_cpus", 1.0),
                num_tpus=opts.get("num_tpus", 0.0),
                resources=opts.get("resources", {}),
                max_concurrency=cfg.max_ongoing_requests,
                max_restarts=-1,
            ).remote(app["blob"], app["init"])
            rv[replica] = code_version
            return replica

        while len(alive) < want:
            alive.append(_start_replica())
            changed = True
        for extra in alive[want:]:
            changed = True
            rv.pop(extra, None)
            try:
                ray_tpu.kill(extra)
            except Exception:
                pass
        app["replicas"] = alive[:want]
        keep = {id(app.get("surge_replica")),
                id((app.get("rollout") or {}).get("draining"))}
        for r in list(rv):
            if r not in app["replicas"] and id(r) not in keep:
                rv.pop(r, None)
        if self._advance_rollout(name, app):
            changed = True
        if changed:
            self._bump(name)

    def _advance_rollout(self, name: str, app: dict) -> bool:
        """One rolling-update step per control-loop tick (reference:
        deployment_state.py's max-surge-1 rollout): start ONE new-version
        replica; once it answers health, pull ONE old-version replica out
        of the topology; kill it only when drained (or after the graceful
        window). Returns True if the topology changed."""
        import time as _t

        rv = app["replica_versions"]
        code_version = app["code_version"]
        ro = app.get("rollout")
        changed = False
        if ro is not None:
            # a drain is in flight. The victim left the topology, but handle
            # caches refresh on a ~5s TTL — keep it ALIVE (still serving)
            # for a propagation grace so stale routers hit a live replica,
            # then kill once idle (hard-capped by the graceful window)
            draining = ro["draining"]
            now = _t.monotonic()
            done = now >= ro["deadline"]
            if not done and now - ro["removed_at"] >= 6.0:
                try:
                    done = ray_tpu.get(draining.drain.remote(),
                                       timeout=5.0) == 0
                except Exception:
                    done = True  # already dead
            if done:
                rv.pop(draining, None)
                try:
                    ray_tpu.kill(draining)
                except Exception:
                    pass
                app["rollout"] = None
            return False
        stale = [r for r in app["replicas"] if rv.get(r, 0) != code_version]
        if not stale:
            return False
        # surge one new-version replica, wait for it to answer health
        surge = app.get("surge_replica")
        if surge is None:
            opts = dict(app["cfg"].ray_actor_options)
            from ray_tpu.serve import api as _api

            surge = _api._Replica.options(
                num_cpus=opts.get("num_cpus", 1.0),
                num_tpus=opts.get("num_tpus", 0.0),
                resources=opts.get("resources", {}),
                max_concurrency=app["cfg"].max_ongoing_requests,
                max_restarts=-1,
            ).remote(app["blob"], app["init"])
            app["surge_replica"] = surge
            rv[surge] = code_version
            return False
        try:
            ray_tpu.get(surge.health.remote(), timeout=5.0)
        except Exception:
            return False  # not ready yet; try next tick
        # swap: new replica enters the topology, oldest stale leaves it
        victim = stale[0]
        replicas = [r for r in app["replicas"] if r is not victim] + [surge]
        app["replicas"] = replicas
        app["surge_replica"] = None
        app["rollout"] = {
            "draining": victim, "removed_at": _t.monotonic(),
            "deadline": _t.monotonic()
            + getattr(app["cfg"], "graceful_shutdown_timeout_s", 30.0)}
        return True

    def _autoscale(self, name: str):
        """Average ongoing requests per replica vs. target, with up/down
        delay smoothing (reference: autoscaling_policy.py)."""
        import time as _t

        app = self.apps[name]
        auto: AutoscalingConfig = app["cfg"].autoscaling
        if auto is None or not app["replicas"]:
            return
        try:
            # peak since the last poll, not an instantaneous sample: a
            # burst that arrives and drains entirely between two 0.5s
            # ticks must still register as load
            ongoing = ray_tpu.get(
                [r.take_ongoing_peak.remote() for r in app["replicas"]],
                timeout=10)
        except Exception:
            return
        avg = sum(ongoing) / max(len(ongoing), 1)
        now = _t.monotonic()
        target = app["target"]
        if avg > auto.target_ongoing_requests and target < auto.max_replicas:
            app["scale_down_since"] = None
            if app["scale_up_since"] is None:
                app["scale_up_since"] = now
            if now - app["scale_up_since"] >= auto.upscale_delay_s:
                # scale to what the load implies, clamped
                want = min(auto.max_replicas, max(
                    target + 1,
                    int(round(avg * len(ongoing)
                              / auto.target_ongoing_requests))))
                app["target"] = want
                app["scale_up_since"] = None
        elif (avg < auto.target_ongoing_requests * 0.5
                and target > auto.min_replicas):
            app["scale_up_since"] = None
            if app["scale_down_since"] is None:
                app["scale_down_since"] = now
            if now - app["scale_down_since"] >= auto.downscale_delay_s:
                app["target"] = max(auto.min_replicas, target - 1)
                app["scale_down_since"] = None
        else:
            app["scale_up_since"] = None
            app["scale_down_since"] = None

    def run_control_loop(self):
        """Blocking reconcile+autoscale loop; started once by serve.run
        (runs on one of the controller's executor threads)."""
        import time as _t

        if self._loop_started:
            return False
        self._loop_started = True
        while self._running:
            for name in list(self.apps):
                try:
                    with self._mutate:
                        if name in self.apps:
                            self._autoscale(name)
                            self._reconcile(name)
                except Exception:
                    pass
            _t.sleep(0.5)
        return True

    def check_replicas(self):
        """One reconcile pass (also available to tests/handles)."""
        for name in list(self.apps):
            with self._mutate:
                if name in self.apps:
                    self._reconcile(name)
        return True

    def get_replicas(self, name: str):
        app = self.apps.get(name)
        if app is None:
            raise KeyError(f"no deployment named {name!r}")
        return list(app["replicas"])

    def get_topology(self, name: str):
        """Versioned replica set for handle caches."""
        app = self.apps.get(name)
        if app is None:
            raise KeyError(f"no deployment named {name!r}")
        return {"version": app["version"], "replicas": list(app["replicas"])}

    async def poll_topology(self, name: str, version: int, timeout: float = 25.0):
        """Long-poll: returns when the replica set version moves past
        ``version`` (or on timeout, with the current state). Async so a
        waiting poller costs no executor thread (reference:
        serve/_private/long_poll.py LongPollHost). 100ms check granularity.
        """
        import time as _t

        deadline = _t.monotonic() + timeout
        while True:
            app = self.apps.get(name)
            if app is None:
                return {"version": -1, "replicas": []}
            if app["version"] != version or _t.monotonic() >= deadline:
                return {"version": app["version"],
                        "replicas": list(app["replicas"])}
            await asyncio.sleep(0.1)

    def delete(self, name: str) -> bool:
        with self._mutate:
            app = self.apps.pop(name, None)
            if app:
                for r in app["replicas"]:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
        with self._cv:
            self._cv.notify_all()
        return True

    def stop_loops(self):
        self._running = False
        return True

    def status(self) -> Dict[str, Any]:
        return {
            name: {"num_replicas": len(app["replicas"]),
                   "target": app["target"],
                   "version": app["version"],
                   "autoscaling": app["cfg"].autoscaling is not None}
            for name, app in self.apps.items()
        }


def _get_controller(create: bool = True):
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        if not create:
            raise
        return _ServeController.options(
            name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1,
            max_concurrency=16, get_if_exists=True).remote()


# ---------------------------------------------------------------------------
# handle + router
# ---------------------------------------------------------------------------


class DeploymentHandle:
    """Client-side router: power-of-two-choices over replica pending counts,
    fed by the controller's versioned topology (long-pollable)."""

    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False):
        self._name = deployment_name
        self._method = method_name
        self._model_id = multiplexed_model_id
        self._stream = stream
        self._replicas: List[Any] = []
        self._version = -1
        self._pending: Dict[Any, int] = {}
        self._last_refresh = 0.0

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        h = DeploymentHandle(
            self._name,
            method_name if method_name is not None else self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._model_id,
            stream if stream is not None else self._stream)
        h._replicas = self._replicas
        h._version = self._version
        h._pending = self._pending
        return h

    def _refresh(self, force: bool = False):
        if not force and self._replicas and time.monotonic() - self._last_refresh < 5.0:
            return
        controller = _get_controller(create=False)
        topo = ray_tpu.get(
            controller.get_topology.remote(self._name), timeout=60)
        self._replicas = topo["replicas"]
        self._version = topo["version"]
        self._pending = {r: 0 for r in self._replicas}
        self._last_refresh = time.monotonic()

    def _long_poll_refresh(self, timeout: float = 25.0):
        """Blocking topology watch (proxies use this in a background
        thread); returns True if the replica set changed."""
        controller = _get_controller(create=False)
        topo = ray_tpu.get(controller.poll_topology.remote(
            self._name, self._version, timeout), timeout=timeout + 30)
        changed = topo["version"] != self._version
        self._replicas = topo["replicas"]
        self._version = topo["version"]
        if changed:
            self._pending = {r: 0 for r in self._replicas}
        self._last_refresh = time.monotonic()
        return changed

    def _pick(self):
        self._refresh()
        if not self._replicas:
            # replicas may be mid-restart: re-ask the controller (it
            # reconciles on demand) before giving up
            deadline = time.monotonic() + 30.0
            while not self._replicas and time.monotonic() < deadline:
                time.sleep(0.2)
                try:
                    self._refresh(force=True)
                except Exception:
                    pass
            if not self._replicas:
                raise RuntimeError(f"deployment {self._name} has no replicas")
        if len(self._replicas) == 1:
            return self._replicas[0]
        a, b = random.sample(self._replicas, 2)
        return a if self._pending.get(a, 0) <= self._pending.get(b, 0) else b

    def remote(self, *args, **kwargs):
        if self._model_id:
            # model multiplexing: the same model id sticks to the same
            # replica so its model cache stays hot (reference:
            # serve/multiplex.py + prefix-aware routing)
            kwargs["_serve_multiplexed_model_id"] = self._model_id
            return self.remote_with_key(self._model_id, *args, **kwargs)
        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        with tracing.profile("serve.route", category="serve",
                             deployment=self._name):
            replica = self._pick()
        _obs()["route"].observe(time.perf_counter() - t0)
        return self._dispatch(replica, args, kwargs)

    def remote_with_key(self, routing_key: str, *args, **kwargs):
        """Consistent routing: the same key prefers the same replica (used by
        prefix-aware LLM routing; falls back to pow-2 with one replica)."""
        import hashlib

        from ray_tpu.util import tracing

        t0 = time.perf_counter()
        with tracing.profile("serve.route", category="serve",
                             deployment=self._name):
            self._refresh()
            if not self._replicas:
                replica = self._pick()  # waits for replicas / raises
            elif len(self._replicas) > 1:
                digest = hashlib.md5(routing_key.encode()).digest()
                replica = self._replicas[
                    int.from_bytes(digest[:4], "little")
                    % len(self._replicas)]
            else:
                replica = self._pick()
        _obs()["route"].observe(time.perf_counter() - t0)
        return self._dispatch(replica, args, kwargs)

    def broadcast(self, method_name: str, *args, timeout: float = 120.0,
                  **kwargs) -> List[Any]:
        """Invoke ``method_name`` once on EVERY current replica (bypasses
        routing). This is the live weight-update primitive: replicas keep
        serving while each applies the call — e.g.
        ``handle.broadcast("update_weights", store_name)`` makes every
        replica pull the newest version from a WeightStore with zero
        dropped requests (the method runs as one more actor task on the
        replica's queue; nothing restarts). Returns one result per replica.
        """
        self._refresh(force=True)
        if not self._replicas:
            raise RuntimeError(f"deployment {self._name} has no replicas")
        blob = cloudpickle.dumps((args, kwargs))
        refs = [r.handle_request.remote(method_name, blob)
                for r in self._replicas]
        return ray_tpu.get(refs, timeout=timeout)

    def _dispatch(self, replica, args, kwargs):
        # pending counters decay by zeroing at each periodic refresh
        self._pending[replica] = self._pending.get(replica, 0) + 1
        # dispatch timestamp rides the request so the replica can record
        # the built-in serve.queue span (popped before user code sees it)
        kwargs = {**kwargs, "_serve_submit_ts": time.time()}
        blob = cloudpickle.dumps((args, kwargs))
        if self._stream:
            # ObjectRefGenerator of chunk refs, produced as the replica
            # yields (reference: handle.options(stream=True))
            return replica.handle_request_streaming.options(
                num_returns="streaming").remote(self._method, blob)
        return replica.handle_request.remote(self._method, blob)

    def __reduce__(self):
        return (DeploymentHandle,
                (self._name, self._method, self._model_id, self._stream))


def get_app_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


# ---------------------------------------------------------------------------
# run / delete / status
# ---------------------------------------------------------------------------


def run(app: Application, name: Optional[str] = None, *,
        _blocking: bool = True) -> DeploymentHandle:
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = _get_controller()
    dep = app.deployment
    deploy_name = name or dep.name
    ray_tpu.get(controller.deploy.remote(
        deploy_name,
        cloudpickle.dumps(dep._target),
        cloudpickle.dumps((app.init_args, app.init_kwargs)),
        cloudpickle.dumps(dep.config)), timeout=600)
    from ray_tpu._private.worker import global_worker

    if global_worker().mode != "local":
        # local mode executes actor calls inline, so the blocking control
        # loop must not start there (health/autoscaling don't apply anyway)
        controller.run_control_loop.remote()  # idempotent; fire-and-forget
    handle = DeploymentHandle(deploy_name)
    handle._refresh(force=True)
    return handle


def delete(name: str):
    controller = _get_controller(create=False)
    ray_tpu.get(controller.delete.remote(name), timeout=60)


def status() -> Dict[str, Any]:
    controller = _get_controller(create=False)
    return ray_tpu.get(controller.status.remote(), timeout=60)


def shutdown():
    try:
        controller = _get_controller(create=False)
    except ValueError:
        return
    for name in list(ray_tpu.get(controller.status.remote(), timeout=60)):
        ray_tpu.get(controller.delete.remote(name), timeout=60)
    ray_tpu.kill(controller)


# ---------------------------------------------------------------------------
# dynamic batching (reference: serve/batching.py)
# ---------------------------------------------------------------------------


def batch(_fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01):
    """Decorator for async methods taking a list of requests: concurrent
    single calls are buffered into one batched invocation."""

    def wrap(fn):
        state = {"queue": [], "event": None, "task": None}

        async def flush(self_ref):
            await asyncio.sleep(batch_wait_timeout_s)
            await do_flush(self_ref)

        async def do_flush(self_ref):
            queue, state["queue"] = state["queue"], []
            state["task"] = None
            if not queue:
                return
            items = [item for item, _ in queue]
            futs = [f for _, f in queue]
            try:
                results = await fn(self_ref, items) if self_ref is not None \
                    else await fn(items)
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                self_ref, item = args
            else:
                self_ref, item = None, args[0]
            fut = asyncio.get_event_loop().create_future()
            state["queue"].append((item, fut))
            if len(state["queue"]) >= max_batch_size:
                if state["task"] is not None:
                    state["task"].cancel()
                    state["task"] = None
                await do_flush(self_ref)
            elif state["task"] is None:
                state["task"] = asyncio.ensure_future(flush(self_ref))
            return await fut

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


# ---------------------------------------------------------------------------
# HTTP proxy (reference: serve/_private/proxy.py)
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _HttpProxy:
    """aiohttp ingress: POST /<deployment> with a JSON body routes to the
    deployment handle and returns the JSON-serialized response."""

    def __init__(self, port: int):
        self.port = port
        self._runner = None

    async def start(self) -> int:
        import json

        from aiohttp import web

        def _route(name, body):
            h = DeploymentHandle(name)
            return ray_tpu.get(h.remote(body), timeout=120)

        def _encode_chunk(chunk) -> bytes:
            if isinstance(chunk, bytes):
                return chunk
            if isinstance(chunk, str):
                return chunk.encode()
            return (json.dumps(chunk) + "\n").encode()

        async def handle(request):
            name = request.match_info["name"]
            try:
                body = await request.json() if request.can_read_body else {}
            except Exception:
                body = {}
            stream = request.query.get("stream") in ("1", "true") or \
                "text/event-stream" in request.headers.get("Accept", "")
            loop = asyncio.get_event_loop()
            if stream:
                # chunked response: each replica yield is flushed to the
                # client as it arrives (reference: proxy.py streaming
                # responses for generator deployments). A thread-safe
                # queue + stop flag, with every block bounded, so a client
                # disconnect can never strand the pump thread
                import queue as _qmod
                import threading as _th

                q: _qmod.Queue = _qmod.Queue(maxsize=8)
                # raylint: disable=ASY002 cross-thread stop flag: loop side only set()/is_set(), never wait()
                stop = _th.Event()
                _END = object()

                def _put(item) -> bool:
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.5)
                            return True
                        except _qmod.Full:
                            continue
                    return False

                def _pump():
                    try:
                        h = DeploymentHandle(name, stream=True)
                        for ref in h.remote(body):
                            if not _put(ray_tpu.get(ref, timeout=120)):
                                return  # client left; drop the stream
                        _put(_END)
                    except Exception as e:
                        _put(RuntimeError(str(e)))

                resp = web.StreamResponse(
                    headers={"Content-Type": "application/octet-stream",
                             "Transfer-Encoding": "chunked"})
                await resp.prepare(request)
                loop.run_in_executor(None, _pump)
                try:
                    while True:
                        try:
                            item = await loop.run_in_executor(
                                None, functools.partial(q.get, timeout=0.5))
                        except _qmod.Empty:
                            continue
                        if item is _END:
                            break
                        if isinstance(item, RuntimeError):
                            await resp.write(_encode_chunk(
                                {"error": str(item)}))
                            break
                        await resp.write(_encode_chunk(item))
                    await resp.write_eof()
                finally:
                    stop.set()
                return resp
            try:
                # route off-loop: handle calls block on the core worker
                result = await loop.run_in_executor(
                    None, functools.partial(_route, name, body))
                return web.json_response({"result": result})
            except Exception as e:
                return web.json_response({"error": str(e)}, status=500)

        app = web.Application()
        app.router.add_post("/{name}", handle)
        app.router.add_get("/-/healthz", lambda r: web.json_response({"ok": True}))
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", self.port)
        await site.start()
        return self.port


def start_http_proxy(port: int = 0) -> int:
    import socket

    if port == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    proxy = _HttpProxy.options(name="serve_http_proxy", lifetime="detached",
                               num_cpus=0.1, max_concurrency=64,
                               get_if_exists=True).remote(port)
    return ray_tpu.get(proxy.start.remote(), timeout=120)

"""ray_tpu.serve: model serving (reference: ray.serve).

Shape of the reference (SURVEY.md §3.5): ``serve.run`` -> ``ServeController``
actor reconciling deployment replica sets (_private/deployment_state.py);
requests enter through a ``DeploymentHandle`` whose router picks a replica by
power-of-two-choices on queue length (request_router/pow_2_router.py:27);
an HTTP proxy actor (aiohttp) fronts handles; ``@serve.batch`` provides
dynamic batching inside replicas (serve/batching.py).
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    DeploymentHandle,
    batch,
    delete,
    deployment,
    get_app_handle,
    run,
    shutdown,
    start_http_proxy,
    status,
)

__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "DeploymentHandle",
    "run",
    "delete",
    "status",
    "shutdown",
    "get_app_handle",
    "batch",
    "start_http_proxy",
]

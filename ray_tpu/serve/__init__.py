"""ray_tpu.serve: model serving (reference: ray.serve).

Shape of the reference (SURVEY.md §3.5): ``serve.run`` -> ``ServeController``
actor reconciling deployment replica sets (_private/deployment_state.py);
requests enter through a ``DeploymentHandle`` whose router picks a replica by
power-of-two-choices on queue length (request_router/pow_2_router.py:27);
an HTTP proxy actor (aiohttp) fronts handles; ``@serve.batch`` provides
dynamic batching inside replicas (serve/batching.py).

The ``serve/autoscale`` subpackage closes the serving loop: demand-driven
autoscaling over windowed rates, SLO-aware ingress admission with
multi-tenant fair queueing, and prefix-cache-aware routing (see
ray_tpu/serve/README.md).
"""

from ray_tpu.serve.autoscale import (
    FairQueue,
    IngressHandle,
    LoadShedError,
    PrefixRouter,
    SLOConfig,
    build_ingress,
)
from ray_tpu.serve.api import (
    Application,
    AutoscalingConfig,
    Deployment,
    DeploymentHandle,
    batch,
    delete,
    deployment,
    get_app_handle,
    run,
    shutdown,
    start_http_proxy,
    status,
)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "deployment",
    "AutoscalingConfig",
    "multiplexed",
    "get_multiplexed_model_id",
    "Deployment",
    "Application",
    "DeploymentHandle",
    "run",
    "delete",
    "status",
    "shutdown",
    "get_app_handle",
    "batch",
    "start_http_proxy",
    "FairQueue",
    "IngressHandle",
    "LoadShedError",
    "PrefixRouter",
    "SLOConfig",
    "build_ingress",
]

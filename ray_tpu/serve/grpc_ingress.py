"""gRPC ingress for Serve (reference: serve/_private/proxy.py gRPC side +
serve/grpc_util.py).

A generic-handler gRPC server (no generated stubs needed): requests are
msgpack-encoded, routed to deployment handles exactly like the HTTP proxy.

  unary    /ray_tpu.serve.ServeAPI/Call    {deployment, method?, body}
  stream   /ray_tpu.serve.ServeAPI/Stream  same request, one message per
                                           replica yield (token streaming)

Runs inside a detached actor (`start_grpc_proxy`), sharing the controller
topology through ordinary DeploymentHandles.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu._private import wire

SERVICE = "ray_tpu.serve.ServeAPI"

# the typed wire codec round-trips numpy arrays, sets, and framework
# structs losslessly (and refuses what it can't represent, instead of
# silently stringifying it)
_encode = wire.dumps
_decode = wire.loads


class _ServeGrpcHandler:
    """grpc.GenericRpcHandler routing to deployment handles."""

    def __init__(self):
        import grpc

        self._grpc = grpc
        self._handles: Dict[tuple, Any] = {}

    def _handle(self, name: str, method: str, stream: bool):
        from ray_tpu.serve.api import DeploymentHandle, _get_controller

        key = (name, method, stream)
        h = self._handles.get(key)
        if h is None:
            # validate the name against the controller first: an unknown
            # deployment must NOT-FOUND immediately instead of pinning a
            # worker thread in the handle's replica-wait loop
            controller = _get_controller(create=False)
            try:
                ray_tpu.get(controller.get_topology.remote(name),
                            timeout=10.0)
            except Exception:
                raise LookupError(f"no deployment named {name!r}")
            h = DeploymentHandle(name, method_name=method or "__call__",
                                 stream=stream)
            self._handles[key] = h
        return h

    def service(self, handler_call_details):
        grpc = self._grpc
        method = handler_call_details.method
        if method == f"/{SERVICE}/Call":
            return grpc.unary_unary_rpc_method_handler(
                self._call, request_deserializer=_decode,
                response_serializer=_encode)
        if method == f"/{SERVICE}/Stream":
            return grpc.unary_stream_rpc_method_handler(
                self._stream, request_deserializer=_decode,
                response_serializer=_encode)
        if method == f"/{SERVICE}/Healthz":
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: {"ok": True},
                request_deserializer=_decode, response_serializer=_encode)
        return None

    def _call(self, request, context):
        grpc = self._grpc
        try:
            h = self._handle(request["deployment"],
                             request.get("method", "__call__"), False)
            result = ray_tpu.get(h.remote(request.get("body", {})),
                                 timeout=float(request.get("timeout", 120.0)))
            return {"result": result}
        except LookupError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except KeyError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"missing field {e}")
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def _stream(self, request, context):
        grpc = self._grpc
        gen = None
        try:
            h = self._handle(request["deployment"],
                             request.get("method", "__call__"), True)
            timeout = float(request.get("timeout", 120.0))
            gen = h.remote(request.get("body", {}))
            for ref in gen:
                if not context.is_active():
                    # client went away: cancel the replica's generator so
                    # it stops producing for a dead stream
                    ray_tpu.cancel(gen)
                    return
                yield {"chunk": ray_tpu.get(ref, timeout=timeout)}
        except LookupError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except KeyError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"missing field {e}")
        except Exception as e:
            if gen is not None and not context.is_active():
                try:
                    ray_tpu.cancel(gen)
                except Exception:
                    pass
            context.abort(grpc.StatusCode.INTERNAL, str(e))


@ray_tpu.remote
class _GrpcProxy:
    def __init__(self, port: int):
        self.port = port
        self._server = None
        self._bound_port = 0

    def start(self) -> int:
        if self._server is not None:
            return self._bound_port  # get_if_exists re-entry: already up
        from concurrent import futures

        import grpc

        handler = _ServeGrpcHandler()

        class _Generic(grpc.GenericRpcHandler):
            def service(self, details):
                return handler.service(details)

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            handlers=(_Generic(),))
        bound = self._server.add_insecure_port(f"127.0.0.1:{self.port}")
        if bound == 0:
            self._server = None
            raise RuntimeError(f"cannot bind gRPC ingress to port {self.port}")
        self._server.start()
        self._bound_port = bound
        return bound


def start_grpc_proxy(port: int = 0) -> int:
    """Start (or reuse) the detached gRPC ingress actor; returns the bound
    port."""
    proxy = _GrpcProxy.options(
        name="serve_grpc_proxy", lifetime="detached", num_cpus=0.1,
        max_concurrency=32, get_if_exists=True).remote(port)
    return ray_tpu.get(proxy.start.remote(), timeout=120)


class ServeGrpcClient:
    """Minimal client for the generic ingress (tests / SDK use)."""

    def __init__(self, address: str):
        import grpc

        self._channel = grpc.insecure_channel(address)
        self._call = self._channel.unary_unary(
            f"/{SERVICE}/Call", request_serializer=_encode,
            response_deserializer=_decode)
        self._stream = self._channel.unary_stream(
            f"/{SERVICE}/Stream", request_serializer=_encode,
            response_deserializer=_decode)

    def call(self, deployment: str, body: Optional[dict] = None,
             method: str = "__call__", timeout: float = 120.0):
        return self._call({"deployment": deployment, "method": method,
                           "body": body or {}, "timeout": timeout},
                          timeout=timeout + 10.0)["result"]

    def stream(self, deployment: str, body: Optional[dict] = None,
               method: str = "__call__", timeout: float = 120.0,
               overall_timeout: Optional[float] = None):
        """`timeout` is the server's PER-CHUNK budget; the gRPC deadline
        for the whole stream is only set when `overall_timeout` is given —
        a healthy long token stream must not be killed client-side."""
        for msg in self._stream({"deployment": deployment, "method": method,
                                 "body": body or {}, "timeout": timeout},
                                timeout=overall_timeout):
            yield msg["chunk"]

    def close(self):
        self._channel.close()

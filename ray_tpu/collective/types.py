"""Collective types (reference: python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum
from dataclasses import dataclass


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    AVERAGE = "average"


class Backend:
    """Supported backends. The reference ships NCCL/gloo/NIXL
    (collective_group/); the TPU-native set is:

    - XLA: jax collectives over ICI within a slice / DCN across slices
      (multi-controller SPMD bootstrapped by jax.distributed);
    - CPU: a store-actor ring for CI, the analog of the reference's
      torch-gloo CPU tier (torch_gloo_collective_group.py).
    """

    XLA = "xla"
    CPU = "cpu"

    @staticmethod
    def validate(name: str) -> str:
        if name not in (Backend.XLA, Backend.CPU):
            raise ValueError(f"unknown collective backend {name!r}")
        return name


@dataclass
class GroupInfo:
    group_name: str
    world_size: int
    backend: str

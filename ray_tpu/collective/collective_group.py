"""Collective group backends.

Reference: python/ray/util/collective/collective_group/ — ``NCCLGroup``
(nccl_collective_group.py:121) with named-actor rendezvous (:29) and the
torch-gloo CPU group. TPU-native replacements:

- ``CpuStoreGroup``: CI tier. A named store actor rendezvouses contributions
  per op sequence number and computes the reduction; correctness-focused,
  hardware-free (the analog of the reference's gloo tier + CPUCommunicator).
- ``XlaGroup``: device tier. Ops execute as jitted ``shard_map`` collectives
  (psum / all_gather / psum_scatter / ppermute) over a 1-D device mesh. In
  multi-host SPMD (bootstrapped via jax.distributed) the same program lowers
  to ICI/DCN collectives; single-process it uses the local device mesh.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import numpy as np

from ray_tpu._private import wire
from ray_tpu.collective.types import ReduceOp

_STORE_PREFIX = "rtpu_collective_store:"


def _reduce_np(arrays: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    stack = np.stack([np.asarray(a) for a in arrays])
    if op == ReduceOp.SUM:
        return stack.sum(axis=0)
    if op == ReduceOp.PRODUCT:
        return np.prod(stack, axis=0)
    if op == ReduceOp.MIN:
        return stack.min(axis=0)
    if op == ReduceOp.MAX:
        return stack.max(axis=0)
    if op == ReduceOp.AVERAGE:
        return stack.mean(axis=0)
    raise ValueError(op)


class CollectiveStore:
    """Named async actor used by the CPU backend for rendezvous + reduction.

    Reference analog: the Rendezvous named actor in
    nccl_collective_group.py:29 (unique-id exchange) — generalized here to
    carry the data plane too, since there is no NCCL under the CPU tier.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._contrib = {}
        self._results = {}
        self._p2p = {}
        self._events = {}  # key -> asyncio.Event (result ready)
        self._p2p_events = {}

    def _event(self, table: dict, key: str):
        import asyncio

        ev = table.get(key)
        if ev is None:
            ev = table[key] = asyncio.Event()
        return ev

    async def collect(self, key: str, rank: int, payload, op_name: Optional[str]):
        import asyncio

        slot = self._contrib.setdefault(key, {})
        slot[rank] = payload
        ev = self._event(self._events, key)
        if len(slot) == self.world_size and key not in self._results:
            ordered = [slot[r] for r in range(self.world_size)]
            if op_name is None:
                self._results[key] = ordered  # allgather
            elif op_name.startswith("qsum:"):
                # quantized allreduce reduce point: dequant-accumulate the
                # uint8+scales contributions in fp32, re-quantize ONCE for
                # the broadcast leg (collective/quant.py) — wire bytes are
                # quantized in BOTH directions
                from ray_tpu.collective import quant

                self._results[key] = quant.reduce_wire_payloads(
                    ordered, op_name[len("qsum:"):])
            else:
                self._results[key] = _reduce_np(ordered, ReduceOp(op_name))
            ev.set()  # wake every parked member — no polling
        if key not in self._results:
            try:
                await asyncio.wait_for(ev.wait(), 300.0)
            except asyncio.TimeoutError:
                raise TimeoutError(f"collective {key} timed out "
                                   f"({len(slot)}/{self.world_size} arrived)")
        result = self._results[key]
        # last leaver cleans up
        slot[f"done{rank}"] = True
        if sum(1 for k in slot if isinstance(k, str)) == self.world_size:
            self._contrib.pop(key, None)
            self._events.pop(key, None)
            res = self._results.pop(key)
            return res
        return result

    async def put_p2p(self, key: str, payload):
        self._p2p[key] = payload
        self._event(self._p2p_events, key).set()
        return True

    async def del_p2p(self, key: str):
        self._p2p.pop(key, None)
        return True

    async def _wait_p2p(self, key: str, timeout: float, consume: bool):
        import asyncio

        deadline = time.monotonic() + timeout
        while key not in self._p2p:
            ev = self._event(self._p2p_events, key)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"p2p {key} timed out")
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                raise TimeoutError(f"p2p {key} timed out")
        if consume:
            self._p2p_events.pop(key, None)
            return self._p2p.pop(key)
        return self._p2p[key]

    async def peek(self, key: str, timeout: float = 300.0):
        """Non-consuming wait (rendezvous metadata, e.g. rank addresses)."""
        return await self._wait_p2p(key, timeout, consume=False)

    async def get_p2p(self, key: str, timeout: float = 300.0):
        return await self._wait_p2p(key, timeout, consume=True)


class CpuStoreGroup:
    def __init__(self, group_name: str, world_size: int, rank: int):
        import ray_tpu

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._seq = 0
        store_cls = ray_tpu.remote(CollectiveStore)
        self.store = store_cls.options(
            name=_STORE_PREFIX + group_name,
            max_concurrency=max(world_size * 2, 8),
            lifetime="detached",
            get_if_exists=True,
            num_cpus=0.1,
        ).remote(world_size)

    def _next_key(self, kind: str) -> str:
        self._seq += 1
        return f"{kind}:{self._seq}"

    def _sync(self, ref):
        import ray_tpu

        return ray_tpu.get(ref, timeout=600)

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        key = self._next_key("ar")
        out = self._sync(self.store.collect.remote(key, self.rank, np.asarray(tensor), op.value))
        return out

    def allreduce_quantized(self, wire: dict, codec) -> dict:
        """Quantized-SUM allreduce: ``wire`` is this rank's encoded
        contribution (``quant.to_wire``); the store dequant-accumulates in
        fp32 and re-quantizes once, so both wire legs carry
        ``codec.bytes_per_element`` per element instead of 4. Returns the
        encoded sum (decode with ``quant.from_wire`` + ``dequantize``)."""
        key = self._next_key("qar")
        return self._sync(self.store.collect.remote(
            key, self.rank, wire, f"qsum:{codec.spec()}"))

    def broadcast_obj(self, payload, src_rank: int = 0):
        """One-to-all broadcast of an arbitrary payload where ONLY the
        source uploads bytes (plain ``broadcast`` collects a full tensor
        from every rank — pointless upload for N-1 of them). The
        compressed param-broadcast leg rides this."""
        key = self._next_key("bco")
        gathered = self._sync(self.store.collect.remote(
            key, self.rank, payload if self.rank == src_rank else None,
            None))
        return gathered[src_rank]

    def allgather(self, tensor):
        key = self._next_key("ag")
        return self._sync(self.store.collect.remote(key, self.rank, np.asarray(tensor), None))

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self.allreduce(tensor, op)
        return out if self.rank == dst_rank else np.asarray(tensor)

    def broadcast(self, tensor, src_rank: int = 0):
        key = self._next_key("bc")
        gathered = self._sync(self.store.collect.remote(key, self.rank, np.asarray(tensor), None))
        return gathered[src_rank]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        reduced = self.allreduce(tensor, op)
        chunks = np.array_split(reduced, self.world_size, axis=0)
        return chunks[self.rank]

    def alltoall(self, tensor):
        """Each rank contributes world_size chunks along axis 0."""
        key = self._next_key("a2a")
        gathered = self._sync(self.store.collect.remote(key, self.rank, np.asarray(tensor), None))
        mine = [np.array_split(g, self.world_size, axis=0)[self.rank] for g in gathered]
        return np.concatenate(mine, axis=0)

    def send(self, tensor, dst_rank: int, tag: int = 0):
        self._sync(self.store.put_p2p.remote(
            f"p2p:{self.rank}:{dst_rank}:{tag}", np.asarray(tensor)))

    def recv(self, src_rank: int, tag: int = 0):
        return self._sync(self.store.get_p2p.remote(f"p2p:{src_rank}:{self.rank}:{tag}"))

    def barrier(self):
        self.allreduce(np.zeros(1, dtype=np.float32))

    def destroy(self):
        pass


class XlaGroup:
    """Collectives lowered to XLA over the device mesh.

    Each op jit-compiles a shard_map program over a 1-D mesh named ``ici``;
    under multi-controller SPMD every group member executes the same program
    and XLA emits ICI (intra-slice) / DCN (cross-slice) collectives. The
    value each member passes in is its per-device-sharded contribution.
    """

    def __init__(self, group_name: str, world_size: int, rank: int,
                 devices: Optional[list] = None):
        from ray_tpu.utils import import_jax

        jax = import_jax()

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        devs = devices if devices is not None else jax.devices()
        if len(devs) % 1 != 0 or not devs:
            raise ValueError("no devices for XlaGroup")
        self._jax = jax
        from jax.sharding import Mesh

        self.mesh = Mesh(np.array(devs), ("ici",))
        self._cache = {}

    def _shmap(self, fn, in_spec, out_spec):
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        return jax.jit(shard_map(
            fn, mesh=self.mesh, in_specs=in_spec, out_specs=out_spec,
            check_rep=False))

    def _op(self, name, builder):
        fn = self._cache.get(name)
        if fn is None:
            fn = builder()
            self._cache[name] = fn
        return fn

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
                    y = jax.lax.psum(x, "ici")
                    if op == ReduceOp.AVERAGE:
                        y = y / self.mesh.size
                elif op == ReduceOp.MAX:
                    y = jax.lax.pmax(x, "ici")
                elif op == ReduceOp.MIN:
                    y = jax.lax.pmin(x, "ici")
                else:
                    raise ValueError(op)
                return y

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"ar_{op}_{x.shape}_{x.dtype}", build)(x)

    def allgather(self, tensor):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                return jax.lax.all_gather(x, "ici", axis=0, tiled=True)

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"ag_{x.shape}_{x.dtype}", build)(x)

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                # same convention as every sibling op: the member's axis-0
                # chunk IS its contribution (shape t); it receives its
                # piece of the reduced chunk (shape t/world), so the
                # assembled output is (t,) with member i's piece at [i]
                return jax.lax.psum_scatter(x, "ici", scatter_dimension=0, tiled=True)

            return self._shmap(f, P("ici"), P("ici"))

        if op != ReduceOp.SUM:
            raise ValueError(
                f"XlaGroup.reducescatter supports SUM only (psum_scatter); "
                f"got {op}")
        x = jnp.asarray(tensor)
        if x.shape[0] % (self.mesh.size ** 2) != 0:
            raise ValueError(
                f"reducescatter input axis 0 ({x.shape[0]}) must be "
                f"divisible by devices^2 ({self.mesh.size ** 2}): axis 0 "
                f"splits into per-member chunks, each scattered again")
        return self._op(f"rs_{x.shape}_{x.dtype}", build)(x)

    def alltoall(self, tensor):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                return jax.lax.all_to_all(x, "ici", split_axis=0, concat_axis=0,
                                          tiled=True)

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"a2a_{x.shape}_{x.dtype}", build)(x)

    def allreduce_quantized(self, wire: dict, codec) -> dict:
        raise NotImplementedError(
            "the XLA tier quantizes INSIDE compiled programs — use "
            "collective.quant.quantized_psum_scatter_1d (or the traced "
            "TrainStepBundle compression= path) instead of the explicit "
            "store-actor exchange; the CPU backend implements this method")

    def broadcast(self, tensor, src_rank: int = 0):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                # mask non-source shards then sum: a broadcast on a mesh
                idx = jax.lax.axis_index("ici")
                masked = jnp.where(idx == src_rank, x, jnp.zeros_like(x))
                return jax.lax.psum(masked, "ici")

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"bc_{src_rank}_{x.shape}_{x.dtype}", build)(x)

    def ppermute(self, tensor, perm):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        perm = tuple(tuple(p) for p in perm)

        def build():
            def f(x):
                return jax.lax.ppermute(x, "ici", perm=perm)

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"pp_{hash(perm)}_{x.shape}_{x.dtype}", build)(x)

    def barrier(self):
        import jax.numpy as jnp

        self.allreduce(jnp.zeros((self.mesh.size,), jnp.float32)).block_until_ready()

    # -- eager p2p via device objects (reference: the accelerator channel
    # tier, torch_tensor_accelerator_channel.py). ICI p2p only exists
    # inside compiled programs (ppermute above); the EAGER tier keeps the
    # tensor resident in the sender's device store and the receiver pulls
    # it directly from the sender's worker — no store hop, no driver hop.

    def _p2p_state(self):
        if getattr(self, "_p2p", None) is None:
            import ray_tpu

            store_cls = ray_tpu.remote(CollectiveStore)
            store = store_cls.options(
                name=_STORE_PREFIX + self.group_name,
                max_concurrency=max(self.world_size * 2, 8),
                lifetime="detached", get_if_exists=True,
                num_cpus=0.1).remote(self.world_size)
            w = ray_tpu._private.worker.global_worker()
            ray_tpu.get(store.put_p2p.remote(
                f"addr:{self.group_name}:{self.rank}", w.address), timeout=60)
            self._p2p = {"store": store, "worker": w,
                         "send_seq": {}, "recv_seq": {}, "addrs": {}}
        return self._p2p

    def _p2p_key(self, src: int, dst: int, tag: int, seq: int) -> bytes:
        import hashlib

        return hashlib.blake2b(
            f"xla_p2p:{self.group_name}:{src}:{dst}:{tag}:{seq}".encode(),
            digest_size=16).digest()

    _P2P_WINDOW = 8  # bounded in-flight sends per (dst, tag)

    def send(self, tensor, dst_rank: int, tag: int = 0,
             timeout: float = 300.0):
        import time as _time

        import jax.numpy as jnp

        import ray_tpu

        st = self._p2p_state()
        k = (dst_rank, tag)
        st["send_seq"][k] = seq = st["send_seq"].get(k, 0) + 1
        key = self._p2p_key(self.rank, dst_rank, tag, seq)
        # backpressure: the receiver frees each slot as it consumes it —
        # block while the message WINDOW sends back is still unconsumed
        old_key = (self._p2p_key(self.rank, dst_rank, tag,
                                 seq - self._P2P_WINDOW)
                   if seq > self._P2P_WINDOW else None)
        deadline = _time.monotonic() + timeout
        while old_key is not None and old_key in st["worker"].device_store:
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"send window to rank {dst_rank} full for {timeout}s")
            _time.sleep(0.002)
        # stays device-resident here until the receiver pulls + frees it
        st["worker"].device_store[key] = jnp.asarray(tensor)
        st.setdefault("sent_keys", set()).add(key)
        # rendezvous flag: the receiver blocks on this instead of hammering
        # our worker with GetDeviceObject polls
        ray_tpu.get(st["store"].put_p2p.remote(key.hex(), True),
                    timeout=timeout)

    def recv(self, src_rank: int, tag: int = 0, timeout: float = 300.0):
        import pickle as _pickle

        import jax.numpy as jnp

        import ray_tpu
        from ray_tpu._private.object_store import read_blob
        from ray_tpu._private.serialization import deserialize

        st = self._p2p_state()
        addr = st["addrs"].get(src_rank)
        if addr is None:
            addr = ray_tpu.get(st["store"].peek.remote(
                f"addr:{self.group_name}:{src_rank}"), timeout=timeout)
            st["addrs"][src_rank] = addr
        k = (src_rank, tag)
        st["recv_seq"][k] = seq = st["recv_seq"].get(k, 0) + 1
        key = self._p2p_key(src_rank, self.rank, tag, seq)
        # wait for the sender's ready flag (one blocking store call),
        # then pull the tensor with a single direct worker RPC
        ray_tpu.get(st["store"].get_p2p.remote(key.hex(), timeout),
                    timeout=timeout + 10)
        w = st["worker"]
        client = w._worker_client(addr)
        reply = wire.loads(w._run(client.call(
            "GetDeviceObject", wire.dumps({"oid": key}),
            timeout=60.0, retries=1), 70.0))
        if reply["status"] != "ok":
            raise RuntimeError(
                f"p2p message from rank {src_rank} tag {tag} vanished "
                f"(sender restarted?)")
        # consume-once: release the sender's device-store slot
        w._run(client.call("FreeDeviceObject",
                           wire.dumps({"oid": key}), timeout=10.0,
                           retries=1), 20.0)
        inband, buffers = read_blob(reply["blob"])
        return jnp.asarray(deserialize(inband, buffers))

    def destroy(self):
        self._cache.clear()
        st = getattr(self, "_p2p", None)
        if st is not None:
            import ray_tpu

            # unconsumed sends would otherwise pin device memory for the
            # worker's lifetime; the store's addr key must go too or a
            # re-created group would peek a stale address
            for key in st.get("sent_keys", ()):
                st["worker"].device_store.pop(key, None)
            try:
                ray_tpu.get(st["store"].del_p2p.remote(
                    f"addr:{self.group_name}:{self.rank}"), timeout=10)
            except Exception:
                pass
            self._p2p = None

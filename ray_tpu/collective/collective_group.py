"""Collective group backends.

Reference: python/ray/util/collective/collective_group/ — ``NCCLGroup``
(nccl_collective_group.py:121) with named-actor rendezvous (:29) and the
torch-gloo CPU group. TPU-native replacements:

- ``CpuStoreGroup``: CI tier. A named store actor rendezvouses contributions
  per op sequence number and computes the reduction; correctness-focused,
  hardware-free (the analog of the reference's gloo tier + CPUCommunicator).
- ``XlaGroup``: device tier. Ops execute as jitted ``shard_map`` collectives
  (psum / all_gather / psum_scatter / ppermute) over a 1-D device mesh. In
  multi-host SPMD (bootstrapped via jax.distributed) the same program lowers
  to ICI/DCN collectives; single-process it uses the local device mesh.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import numpy as np

from ray_tpu.collective.types import ReduceOp

_STORE_PREFIX = "rtpu_collective_store:"


def _reduce_np(arrays: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    stack = np.stack([np.asarray(a) for a in arrays])
    if op == ReduceOp.SUM:
        return stack.sum(axis=0)
    if op == ReduceOp.PRODUCT:
        return np.prod(stack, axis=0)
    if op == ReduceOp.MIN:
        return stack.min(axis=0)
    if op == ReduceOp.MAX:
        return stack.max(axis=0)
    if op == ReduceOp.AVERAGE:
        return stack.mean(axis=0)
    raise ValueError(op)


class CollectiveStore:
    """Named async actor used by the CPU backend for rendezvous + reduction.

    Reference analog: the Rendezvous named actor in
    nccl_collective_group.py:29 (unique-id exchange) — generalized here to
    carry the data plane too, since there is no NCCL under the CPU tier.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._contrib = {}
        self._results = {}
        self._p2p = {}

    async def collect(self, key: str, rank: int, payload, op_name: Optional[str]):
        import asyncio

        slot = self._contrib.setdefault(key, {})
        slot[rank] = payload
        if len(slot) == self.world_size and key not in self._results:
            ordered = [slot[r] for r in range(self.world_size)]
            if op_name is None:
                self._results[key] = ordered  # allgather
            else:
                self._results[key] = _reduce_np(ordered, ReduceOp(op_name))
        deadline = time.monotonic() + 300.0
        while key not in self._results:
            if time.monotonic() > deadline:
                raise TimeoutError(f"collective {key} timed out "
                                   f"({len(slot)}/{self.world_size} arrived)")
            await asyncio.sleep(0.002)
        result = self._results[key]
        # last leaver cleans up
        slot[f"done{rank}"] = True
        if sum(1 for k in slot if isinstance(k, str)) == self.world_size:
            self._contrib.pop(key, None)
            res = self._results.pop(key)
            return res
        return result

    async def put_p2p(self, key: str, payload):
        self._p2p[key] = payload
        return True

    async def get_p2p(self, key: str, timeout: float = 300.0):
        import asyncio

        deadline = time.monotonic() + timeout
        while key not in self._p2p:
            if time.monotonic() > deadline:
                raise TimeoutError(f"recv {key} timed out")
            await asyncio.sleep(0.002)
        return self._p2p.pop(key)


class CpuStoreGroup:
    def __init__(self, group_name: str, world_size: int, rank: int):
        import ray_tpu

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._seq = 0
        store_cls = ray_tpu.remote(CollectiveStore)
        self.store = store_cls.options(
            name=_STORE_PREFIX + group_name,
            max_concurrency=max(world_size * 2, 8),
            lifetime="detached",
            get_if_exists=True,
            num_cpus=0.1,
        ).remote(world_size)

    def _next_key(self, kind: str) -> str:
        self._seq += 1
        return f"{kind}:{self._seq}"

    def _sync(self, ref):
        import ray_tpu

        return ray_tpu.get(ref, timeout=600)

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        key = self._next_key("ar")
        out = self._sync(self.store.collect.remote(key, self.rank, np.asarray(tensor), op.value))
        return out

    def allgather(self, tensor):
        key = self._next_key("ag")
        return self._sync(self.store.collect.remote(key, self.rank, np.asarray(tensor), None))

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self.allreduce(tensor, op)
        return out if self.rank == dst_rank else np.asarray(tensor)

    def broadcast(self, tensor, src_rank: int = 0):
        key = self._next_key("bc")
        gathered = self._sync(self.store.collect.remote(key, self.rank, np.asarray(tensor), None))
        return gathered[src_rank]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        reduced = self.allreduce(tensor, op)
        chunks = np.array_split(reduced, self.world_size, axis=0)
        return chunks[self.rank]

    def alltoall(self, tensor):
        """Each rank contributes world_size chunks along axis 0."""
        key = self._next_key("a2a")
        gathered = self._sync(self.store.collect.remote(key, self.rank, np.asarray(tensor), None))
        mine = [np.array_split(g, self.world_size, axis=0)[self.rank] for g in gathered]
        return np.concatenate(mine, axis=0)

    def send(self, tensor, dst_rank: int, tag: int = 0):
        self._sync(self.store.put_p2p.remote(
            f"p2p:{self.rank}:{dst_rank}:{tag}", np.asarray(tensor)))

    def recv(self, src_rank: int, tag: int = 0):
        return self._sync(self.store.get_p2p.remote(f"p2p:{src_rank}:{self.rank}:{tag}"))

    def barrier(self):
        self.allreduce(np.zeros(1, dtype=np.float32))

    def destroy(self):
        pass


class XlaGroup:
    """Collectives lowered to XLA over the device mesh.

    Each op jit-compiles a shard_map program over a 1-D mesh named ``ici``;
    under multi-controller SPMD every group member executes the same program
    and XLA emits ICI (intra-slice) / DCN (cross-slice) collectives. The
    value each member passes in is its per-device-sharded contribution.
    """

    def __init__(self, group_name: str, world_size: int, rank: int,
                 devices: Optional[list] = None):
        from ray_tpu.utils import import_jax

        jax = import_jax()

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        devs = devices if devices is not None else jax.devices()
        if len(devs) % 1 != 0 or not devs:
            raise ValueError("no devices for XlaGroup")
        self._jax = jax
        from jax.sharding import Mesh

        self.mesh = Mesh(np.array(devs), ("ici",))
        self._cache = {}

    def _shmap(self, fn, in_spec, out_spec):
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        return jax.jit(shard_map(
            fn, mesh=self.mesh, in_specs=in_spec, out_specs=out_spec,
            check_rep=False))

    def _op(self, name, builder):
        fn = self._cache.get(name)
        if fn is None:
            fn = builder()
            self._cache[name] = fn
        return fn

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
                    y = jax.lax.psum(x, "ici")
                    if op == ReduceOp.AVERAGE:
                        y = y / self.mesh.size
                elif op == ReduceOp.MAX:
                    y = jax.lax.pmax(x, "ici")
                elif op == ReduceOp.MIN:
                    y = jax.lax.pmin(x, "ici")
                else:
                    raise ValueError(op)
                return y

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"ar_{op}_{x.shape}_{x.dtype}", build)(x)

    def allgather(self, tensor):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                return jax.lax.all_gather(x, "ici", axis=0, tiled=True)

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"ag_{x.shape}_{x.dtype}", build)(x)

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                # each member contributes its full array; replicated in-spec
                # models that in single-process simulation
                return jax.lax.psum_scatter(x, "ici", scatter_dimension=0, tiled=True)

            return self._shmap(f, P(), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"rs_{x.shape}_{x.dtype}", build)(x)

    def alltoall(self, tensor):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                return jax.lax.all_to_all(x, "ici", split_axis=0, concat_axis=0,
                                          tiled=True)

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"a2a_{x.shape}_{x.dtype}", build)(x)

    def broadcast(self, tensor, src_rank: int = 0):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        def build():
            def f(x):
                # mask non-source shards then sum: a broadcast on a mesh
                idx = jax.lax.axis_index("ici")
                masked = jnp.where(idx == src_rank, x, jnp.zeros_like(x))
                return jax.lax.psum(masked, "ici")

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"bc_{src_rank}_{x.shape}_{x.dtype}", build)(x)

    def ppermute(self, tensor, perm):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        perm = tuple(tuple(p) for p in perm)

        def build():
            def f(x):
                return jax.lax.ppermute(x, "ici", perm=perm)

            return self._shmap(f, P("ici"), P("ici"))

        x = jnp.asarray(tensor)
        return self._op(f"pp_{hash(perm)}_{x.shape}_{x.dtype}", build)(x)

    def barrier(self):
        import jax.numpy as jnp

        self.allreduce(jnp.zeros((self.mesh.size,), jnp.float32)).block_until_ready()

    def send(self, tensor, dst_rank: int, tag: int = 0):
        raise NotImplementedError(
            "XLA p2p uses ppermute inside compiled programs; for eager p2p "
            "between actors use the cpu backend or device channels")

    recv = send

    def destroy(self):
        self._cache.clear()

"""Bucketed asynchronous gradient collectives + cross-replica sharded update.

The explicit-collective tier of the overlapped train step (the GSPMD tier
lives in ``parallel/train.py``): a size-bounded bucket plan over the grad
tree (layer order), an async reducer that ships each bucket through
``ray_tpu.collective`` ops on a background thread — so bucket i's
allreduce runs while the caller is still producing bucket i+1's grads or
applying bucket i-1's update — and a cross-replica **sharded optimizer**
(arxiv 2004.13336): each replica owns ~1/N of the buckets, keeps optimizer
state ONLY for its buckets, applies the update for them, and broadcasts
the refreshed params — optimizer-state memory drops N× on the data axis.

Bucketing rule: leaves are walked in tree (layer) order and packed
greedily into buckets of at most ``bucket_bytes``; a single leaf larger
than the bound becomes its own bucket (never split across buckets at this
tier — intra-leaf sharding is the GSPMD tier's job). Owners are assigned
greedily to the least-loaded rank (deterministic tie-break by rank) so the
per-replica update work and opt-state bytes stay balanced.

Global-norm clip in the sharded update is computed from shard-local
sqnorms: each owner computes per-leaf sqnorms for its buckets (full-leaf
reduction, same shapes as the fused reference), the per-leaf scalars are
allgathered into one vector ordered by global leaf index, and every rank
folds that vector in tree order — the same association
``optax.clip_by_global_norm`` uses, so the clip factor matches the
single-process reference bit-for-bit given bitwise-equal reduced grads.

Every bucket collective lands as a ``train.bucket_allreduce`` span
(nested under whatever span is active at submit time, e.g.
``train.fwd_bwd``) and in the ``ray_tpu.train.allreduce_seconds``
histogram, so ``/api/timeline`` shows the overlap.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_metrics_lock = threading.Lock()
_metrics: Optional[dict] = None

DEFAULT_BUCKET_BYTES = 32 << 20


def _obs() -> dict:
    """Bucket-collective metrics on the shared registry (lazy: importing
    this module must not pull the metrics stack into forked workers)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Histogram

            _metrics = {
                "allreduce": Histogram(
                    "ray_tpu.train.allreduce_seconds",
                    "wall time of one grad-bucket collective (allreduce/"
                    "reduce/broadcast) on the async reducer thread",
                    boundaries=[0.0001, 0.001, 0.01, 0.1, 1, 10]),
                "bucket_bytes": Histogram(
                    "ray_tpu.train.bucket_bytes",
                    "payload bytes of one grad bucket shipped through the "
                    "collective layer",
                    boundaries=[1 << 10, 1 << 16, 1 << 20, 1 << 24, 1 << 28]),
                "buckets": Counter(
                    "ray_tpu.train.buckets_reduced",
                    "grad buckets reduced through the async bucketed "
                    "collective path"),
                "quant_saved": Counter(
                    "ray_tpu.train.quant_bytes_saved",
                    "wire bytes saved by the quantized collective tier vs "
                    "shipping fp32 on both legs (contribute + broadcast)"),
                "quant_encode": Histogram(
                    "ray_tpu.train.quant_encode_seconds",
                    "CPU time spent encoding/decoding one quantized bucket "
                    "payload (quantize + error-feedback + dequantize)",
                    boundaries=[0.00001, 0.0001, 0.001, 0.01, 0.1]),
            }
        return _metrics


@dataclass(frozen=True)
class Bucket:
    """One size-bounded group of grad leaves reduced as a unit."""

    index: int
    paths: Tuple[str, ...]
    nbytes: int
    owner: int  # rank owning this bucket's optimizer shard


@dataclass
class BucketPlan:
    """Layer-ordered bucket partition of a grad tree."""

    buckets: List[Bucket]
    bucket_bytes: int
    world_size: int
    leaf_order: Tuple[str, ...] = ()  # global leaf order (clip fold order)

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def owned(self, rank: int) -> List[Bucket]:
        return [b for b in self.buckets if b.owner == rank]

    def total_bytes(self) -> int:
        return sum(b.nbytes for b in self.buckets)

    def bytes_per_rank(self) -> List[int]:
        out = [0] * self.world_size
        for b in self.buckets:
            out[b.owner] += b.nbytes
        return out

    def stats(self) -> Dict[str, Any]:
        sizes = [b.nbytes for b in self.buckets] or [0]
        return {
            "num_buckets": self.num_buckets,
            "bucket_bytes": self.bucket_bytes,
            "total_bytes": self.total_bytes(),
            "max_bucket_bytes": max(sizes),
            "min_bucket_bytes": min(sizes),
            "bytes_per_rank": self.bytes_per_rank(),
        }


def leaf_meta(tree: Any) -> "Dict[str, Tuple[Tuple[int, ...], Any]]":
    """``{path: (shape, dtype)}`` for every array leaf, in tree order
    (dicts iterate insertion-ordered; flax param trees are layer-ordered,
    which makes bucket order == layer order)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for key, leaf in flat:
        path = jax.tree_util.keystr(key)
        out[path] = (tuple(getattr(leaf, "shape", ())),
                     np.dtype(getattr(leaf, "dtype", np.float32)))
    return out


def plan_buckets(meta: "Dict[str, Tuple[Tuple[int, ...], Any]]",
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 world_size: int = 1) -> BucketPlan:
    """Pack leaves (in the given order) into size-bounded buckets.

    - many tiny leaves pack into one bucket until ``bucket_bytes`` would
      be exceeded;
    - one giant leaf larger than ``bucket_bytes`` becomes its own bucket
      (leaves are never split at this tier);
    - owners balance bytes greedily across ``world_size`` ranks.
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    groups: List[Tuple[List[str], int]] = []
    cur: List[str] = []
    cur_bytes = 0
    for path, (shape, dtype) in meta.items():
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize \
            if shape else np.dtype(dtype).itemsize
        if cur and cur_bytes + nbytes > bucket_bytes:
            groups.append((cur, cur_bytes))
            cur, cur_bytes = [], 0
        cur.append(path)
        cur_bytes += nbytes
        if cur_bytes >= bucket_bytes:  # giant leaf or a full pack
            groups.append((cur, cur_bytes))
            cur, cur_bytes = [], 0
    if cur:
        groups.append((cur, cur_bytes))
    load = [0] * max(world_size, 1)
    buckets = []
    for i, (paths, nbytes) in enumerate(groups):
        owner = min(range(len(load)), key=lambda r: (load[r], r))
        load[owner] += nbytes
        buckets.append(Bucket(index=i, paths=tuple(paths), nbytes=nbytes,
                              owner=owner))
    return BucketPlan(buckets=buckets, bucket_bytes=bucket_bytes,
                      world_size=max(world_size, 1),
                      leaf_order=tuple(meta.keys()))


def _pack(leaves: Dict[str, np.ndarray]) -> List[Tuple[Any, np.ndarray, list]]:
    """Concatenate same-dtype leaves into flat vectors (one collective op
    per dtype instead of per leaf)."""
    by_dtype: Dict[Any, list] = {}
    for path, arr in leaves.items():
        arr = np.asarray(arr)
        by_dtype.setdefault(arr.dtype, []).append((path, arr))
    out = []
    for dtype, items in by_dtype.items():
        flat = np.concatenate([a.reshape(-1) for _, a in items]) \
            if items else np.zeros(0, dtype)
        out.append((dtype, flat, [(p, a.shape) for p, a in items]))
    return out


def _unpack(packed: List[Tuple[Any, np.ndarray, list]]
            ) -> Dict[str, np.ndarray]:
    out = {}
    for _, flat, layout in packed:
        off = 0
        for path, shape in layout:
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            out[path] = flat[off:off + n].reshape(shape)
            off += n
    return out


class BucketHandle:
    """Future for one submitted bucket collective."""

    def __init__(self, bucket: Bucket):
        self.bucket = bucket
        self._done = threading.Event()
        self._result: Optional[Dict[str, np.ndarray]] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float = 300.0) -> Dict[str, np.ndarray]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"bucket {self.bucket.index} collective did not complete "
                f"within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def _set(self, result=None, error=None):
        self._result, self._error = result, error
        self._done.set()


class AsyncBucketReducer:
    """Ship grad buckets through ``ray_tpu.collective`` on a background
    thread, in deterministic bucket order (every rank must submit the
    same buckets in the same order — the collective store matches ops by
    sequence number).

    The group named here should be DEDICATED to this reducer: interleaving
    other collectives on the same group from other threads would desync
    the op sequence across ranks.
    """

    def __init__(self, group_name: str, plan: BucketPlan, *,
                 average: bool = False, compression: Any = None):
        from ray_tpu.collective.quant import ErrorFeedback, resolve_codec

        self.group_name = group_name
        self.plan = plan
        self.average = average
        # strictly opt-in: with compression=None the reduce path below is
        # byte-identical to the uncompressed tier (regression-asserted)
        self.codec = resolve_codec(compression)
        self._ef = ErrorFeedback(self.codec) if self.codec else None
        self._wire_lock = threading.Lock()
        self._wire = {"bytes_fp32_equiv": 0, "bytes_wire": 0,
                      "buckets_quantized": 0, "encode_s": 0.0}
        self._queue: "List[Tuple[Bucket, Dict[str, np.ndarray], Any, BucketHandle]]" = []
        self._cv = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name=f"bucket-reducer-{group_name}", daemon=True)
        self._thread.start()

    # -- submission ------------------------------------------------------

    def submit(self, bucket: Bucket, leaves: Dict[str, np.ndarray]
               ) -> BucketHandle:
        """Queue one bucket's allreduce; returns immediately. The caller
        keeps computing (backward of later buckets / optimizer of earlier
        ones) while the collective runs."""
        from ray_tpu.util import tracing

        handle = BucketHandle(bucket)
        ctx = tracing.current_context()
        with self._cv:
            if self._stop:
                raise RuntimeError("reducer is shut down")
            self._queue.append((bucket, leaves, ctx, handle))
            self._cv.notify()
        return handle

    def reduce_tree(self, tree: Any, timeout: float = 300.0) -> Any:
        """Convenience: bucket-partition a full grad tree, submit every
        bucket (async), wait for all, and reassemble the reduced tree."""
        import jax

        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        by_path = {jax.tree_util.keystr(k): np.asarray(v) for k, v in flat}
        handles = [
            self.submit(b, {p: by_path[p] for p in b.paths})
            for b in self.plan.buckets
        ]
        reduced: Dict[str, np.ndarray] = {}
        for h in handles:
            reduced.update(h.result(timeout))
        leaves = [reduced[jax.tree_util.keystr(k)] for k, _ in flat]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- worker ----------------------------------------------------------

    def _run(self):
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(1.0)
                if self._stop and not self._queue:
                    return
                bucket, leaves, ctx, handle = self._queue.pop(0)
            try:
                handle._set(result=self._reduce(bucket, leaves, ctx))
            except BaseException as e:  # surfaced via handle.result()
                handle._set(error=e)

    def _reduce(self, bucket: Bucket, leaves: Dict[str, np.ndarray], ctx
                ) -> Dict[str, np.ndarray]:
        from ray_tpu import collective as col
        from ray_tpu.util import tracing

        obs = _obs()
        t0 = time.time()
        packed = _pack(leaves)
        out = []
        wire_up = wire_down = 0
        for dtype, flat, layout in packed:
            if self.codec is not None and np.issubdtype(dtype, np.floating):
                reduced, up, down = self._reduce_quantized(bucket, dtype,
                                                           flat)
                reduced = reduced.astype(dtype, copy=False)
                wire_up += up
                wire_down += down
            else:
                reduced = np.asarray(col.allreduce(
                    flat, group_name=self.group_name))
            if self.average:
                reduced = reduced / self.plan.world_size
            out.append((dtype, reduced, layout))
        result = _unpack(out)
        end = time.time()
        span_extra = {}
        if self.codec is not None:
            span_extra = {"compression": self.codec.name,
                          "wire_bytes": wire_up + wire_down}
        tracing.record_span(
            "train.bucket_allreduce", t0, end, category="train",
            trace_id=ctx[0] if ctx else tracing.new_trace_id(),
            span_id=tracing.new_span_id(),
            parent_id=ctx[1] if ctx else None,
            bucket=bucket.index, nbytes=bucket.nbytes, owner=bucket.owner,
            leaves=len(bucket.paths), **span_extra)
        obs["allreduce"].observe(end - t0)
        obs["bucket_bytes"].observe(bucket.nbytes)
        obs["buckets"].inc()
        return result

    def _reduce_quantized(self, bucket: Bucket, dtype, flat: np.ndarray
                          ) -> Tuple[np.ndarray, int, int]:
        """One dtype-vector's quantized allreduce: error-feedback encode
        on the contribute leg, fp32 dequant-accumulate at the store's
        reduce point, one re-quantized broadcast leg (see quant.py)."""
        from ray_tpu import collective as col
        from ray_tpu.collective import quant

        obs = _obs()
        t0 = time.perf_counter()
        qt = self._ef.encode((bucket.index, str(dtype)), flat)
        wire = quant.to_wire(qt)
        enc_s = time.perf_counter() - t0
        out_wire = col.allreduce_quantized(wire, self.codec,
                                           group_name=self.group_name)
        t1 = time.perf_counter()
        reduced = quant.dequantize(quant.from_wire(out_wire)).astype(
            np.float32, copy=False)
        enc_s += time.perf_counter() - t1
        up, down = quant.wire_nbytes(wire), quant.wire_nbytes(out_wire)
        fp32_equiv = int(flat.astype(np.float32, copy=False).nbytes) * 2
        obs["quant_encode"].observe(enc_s)
        obs["quant_saved"].inc(max(fp32_equiv - (up + down), 0))
        with self._wire_lock:
            self._wire["bytes_fp32_equiv"] += fp32_equiv
            self._wire["bytes_wire"] += up + down
            self._wire["buckets_quantized"] += 1
            self._wire["encode_s"] += enc_s
        return reduced, up, down

    def wire_stats(self) -> Dict[str, Any]:
        """Cumulative wire-byte accounting of the quantized path (both
        legs; ``bytes_fp32_equiv`` is what the same traffic costs
        uncompressed). Empty-ish when compression is off."""
        with self._wire_lock:
            s = dict(self._wire)
        s["compression"] = self.codec.name if self.codec else None
        if s["bytes_wire"]:
            s["wire_reduction_x"] = round(
                s["bytes_fp32_equiv"] / s["bytes_wire"], 2)
        return s

    def shutdown(self, timeout: float = 30.0):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout)


def init_sharded_optimizer_groups(world_size: int, rank: int,
                                  backend: str = "cpu",
                                  base_name: str = "train.grads"):
    """Initialize the two collective groups a ``ShardedBucketOptimizer``
    uses in this process: ``base_name`` (dedicated to the async bucket
    reducer) and ``base_name + ".norm"`` (clip allgather + param
    broadcasts, which run on the caller thread)."""
    from ray_tpu import collective as col

    col.init_collective_group(world_size, rank, backend=backend,
                              group_name=base_name)
    col.init_collective_group(world_size, rank, backend=backend,
                              group_name=f"{base_name}.norm")
    return base_name


class ShardedBucketOptimizer:
    """Cross-replica sharded optimizer update over a bucket plan (the
    multi-controller tier of arxiv 2004.13336).

    Rank r keeps optimizer state ONLY for the buckets it owns (~1/N of
    the params by bytes). One ``step``:

    1. every bucket's grads are reduced (async, pipelined) — owners end
       up with the summed grads for their buckets;
    2. owners compute per-leaf sqnorms for the coordinated global-norm
       clip; the per-leaf scalars are allgathered and folded in global
       leaf order on every rank (bit-identical association to
       ``optax.clip_by_global_norm`` over the full tree);
    3. owners apply the optax update for their buckets (per-bucket opt
       state; adam-family transforms are per-leaf so bucket-wise apply
       matches whole-tree apply bit-for-bit);
    4. updated params broadcast from each owner — the broadcast of bucket
       i overlaps the update compute of bucket i+1.

    ``optimizer`` must be a PER-LEAF optax transform (adam family,
    sgd/momentum, weight decay): ``update()`` runs once per owned bucket
    subtree, so a cross-leaf transform (``optax.clip_by_global_norm``)
    buried in the chain would clip per-bucket norms instead of the global
    one — pass ``clip_global_norm=`` for the coordinated clip.
    """

    def __init__(self, group_name: str, plan: BucketPlan, rank: int,
                 optimizer, params: Any, *, clip_global_norm:
                 Optional[float] = None, grad_scale: float = 1.0,
                 compression: Any = None):
        import jax

        from ray_tpu.collective.quant import ErrorFeedback, resolve_codec

        self.group_name = group_name
        self.plan = plan
        self.rank = rank
        self.optimizer = optimizer
        self.clip = clip_global_norm
        self.grad_scale = grad_scale
        # compression=None keeps BOTH legs on the PR 12 fp32 path
        # (bit-identical collective sequence; regression-asserted); a codec
        # quantizes the grad reduce (inside the reducer, with error
        # feedback) AND the param-refresh broadcast — which then ships the
        # quantized param DELTA (new - old) so precision loss is bounded
        # by one step's update and error-fed into the next broadcast.
        self.codec = resolve_codec(compression)
        self._bcast_ef = ErrorFeedback(self.codec) if self.codec else None
        flat, self._treedef = jax.tree_util.tree_flatten_with_path(params)
        self._paths = [jax.tree_util.keystr(k) for k, _ in flat]
        self._leaf_idx = {p: i for i, p in enumerate(self._paths)}
        self._by_path = {p: np.asarray(v) for p, v in
                         zip(self._paths, (v for _, v in flat))}
        self.opt_state = {
            b.index: optimizer.init(self._subtree(b))
            for b in plan.owned(rank)
        }
        self._reducer = AsyncBucketReducer(group_name, plan,
                                           compression=compression)

    def _subtree(self, bucket: Bucket) -> Dict[str, np.ndarray]:
        return {p: self._by_path[p] for p in bucket.paths}

    def opt_state_bytes(self) -> int:
        import jax

        return sum(np.asarray(leaf).nbytes
                   for state in self.opt_state.values()
                   for leaf in jax.tree_util.tree_leaves(state))

    def step(self, grads: Any) -> Tuple[Any, Dict[str, Any]]:
        """One sharded update. ``grads`` is this rank's LOCAL grad tree
        (summed across ranks by the reducer; pre-scale with
        ``grad_scale``, e.g. 1/world for a mean). Returns the updated
        full param tree (identical on every rank) + stats."""
        import jax
        from ray_tpu import collective as col

        flat, _ = jax.tree_util.tree_flatten_with_path(grads)
        gmap = {jax.tree_util.keystr(k): np.asarray(v) for k, v in flat}
        if set(gmap) != set(self._paths):
            raise ValueError("grad tree does not match the param tree the "
                             "sharded optimizer was built over")
        t0 = time.perf_counter()
        handles = [self._reducer.submit(b, {p: gmap[p] for p in b.paths})
                   for b in self.plan.buckets]
        reduced: Dict[int, Dict[str, np.ndarray]] = {}
        for h in handles:
            res = h.result()
            if self.grad_scale != 1.0:
                res = {p: a * np.asarray(self.grad_scale, a.dtype)
                       for p, a in res.items()}
            reduced[h.bucket.index] = res
        allreduce_s = time.perf_counter() - t0
        scale = np.float32(1.0)
        gnorm = None
        if self.clip is not None:
            # shard-local per-leaf sqnorms -> allgather -> fold in global
            # leaf order (every rank computes the same factor bitwise)
            local = np.zeros(len(self._paths), np.float32)
            for b in self.plan.owned(self.rank):
                for p in b.paths:
                    a = reduced[b.index][p].astype(np.float32, copy=False)
                    local[self._leaf_idx[p]] = np.sum(np.square(a))
            gathered = np.asarray(col.allgather(
                local, group_name=f"{self.group_name}.norm"))
            per_leaf = gathered.sum(axis=0)  # disjoint -> sum recovers all
            acc = np.float32(0.0)
            for v in per_leaf:
                acc = np.float32(acc + np.float32(v))
            gnorm = np.float32(np.sqrt(acc))
            scale = np.float32(self.clip / max(float(gnorm), self.clip))
        import optax

        t1 = time.perf_counter()
        owned = {b.index: b for b in self.plan.owned(self.rank)}
        updated: Dict[str, np.ndarray] = {}
        for idx, bucket in owned.items():
            g = {p: (reduced[idx][p] * scale).astype(reduced[idx][p].dtype)
                 for p in bucket.paths}
            p_sub = self._subtree(bucket)
            upd, self.opt_state[idx] = self.optimizer.update(
                g, self.opt_state[idx], p_sub)
            new = optax.apply_updates(p_sub, upd)
            updated.update(new)
        optimizer_s = time.perf_counter() - t1
        # broadcast refreshed buckets from their owners (deterministic
        # bucket order on every rank)
        t2 = time.perf_counter()
        bcast_wire = bcast_fp32 = 0
        for b in self.plan.buckets:
            if self.codec is not None:
                up, down = self._broadcast_bucket_quantized(b, updated)
                bcast_wire += up + down
                bcast_fp32 += b.nbytes
                continue
            packed = _pack({p: (updated[p] if b.owner == self.rank
                                else self._by_path[p])
                            for p in b.paths})
            out = []
            for dtype, flatv, layout in packed:
                res = np.asarray(col.broadcast(
                    flatv, src_rank=b.owner,
                    group_name=f"{self.group_name}.norm"))
                out.append((dtype, res, layout))
            for p, a in _unpack(out).items():
                self._by_path[p] = a
        broadcast_s = time.perf_counter() - t2
        leaves = [self._by_path[p] for p in self._paths]
        tree = jax.tree_util.tree_unflatten(self._treedef, leaves)
        stats = {
            "allreduce_s": allreduce_s,
            "optimizer_s": optimizer_s,
            "broadcast_s": broadcast_s,
            "grad_norm": None if gnorm is None else float(gnorm),
            "clip_scale": float(scale),
            "opt_state_bytes": self.opt_state_bytes(),
            "owned_buckets": sorted(owned),
        }
        if self.codec is not None:
            stats["compression"] = self.codec.name
            stats["broadcast_wire_bytes"] = bcast_wire
            stats["broadcast_fp32_bytes"] = bcast_fp32
            stats["reduce_wire"] = self._reducer.wire_stats()
        return tree, stats

    def _broadcast_bucket_quantized(self, bucket: Bucket,
                                    updated: Dict[str, np.ndarray]
                                    ) -> Tuple[int, int]:
        """The compressed param-refresh leg: the owner ships the quantized
        param DELTA of its bucket (with error feedback), every rank —
        owner included — applies ``base + dequant(delta)`` to its local
        copy, so ranks stay bitwise identical while the wire carries
        ~1 byte/element. The owner's exact-vs-broadcast difference is the
        EF residual, folded into the next step's delta."""
        from ray_tpu import collective as col
        from ray_tpu.collective import quant

        group = col.get_group(f"{self.group_name}.norm")
        # quantized deltas only make sense for float leaves — an int32
        # counter whose +1 delta dequantizes to 0.98 would truncate back
        # to base and never advance; non-float leaves ship their raw
        # updated values (same guard as the reduce leg's _pack dispatch)
        float_paths = [p for p in bucket.paths
                       if np.issubdtype(self._by_path[p].dtype,
                                        np.floating)]
        fset = set(float_paths)
        raw_paths = [p for p in bucket.paths if p not in fset]
        payload = None
        enc_s = 0.0
        if bucket.owner == self.rank:
            t0 = time.perf_counter()
            deltas = {p: updated[p].astype(np.float32)
                      - self._by_path[p].astype(np.float32)
                      for p in float_paths}
            items = []
            for dtype, flatv, layout in _pack(deltas):
                qt = self._bcast_ef.encode(("bcast", bucket.index,
                                            str(dtype)), flatv)
                items.append((str(dtype), quant.to_wire(qt), layout))
            enc_s += time.perf_counter() - t0
            payload = (items, {p: updated[p] for p in raw_paths})
        items, raw = group.broadcast_obj(payload, src_rank=bucket.owner)
        t1 = time.perf_counter()
        up = down = 0
        for dtype, wire, layout in items:
            nb = quant.wire_nbytes(wire)
            down += nb
            if bucket.owner == self.rank:
                up += nb
            delta = quant.dequantize(quant.from_wire(wire))
            off = 0
            for p, shape in layout:
                n = int(np.prod(shape, dtype=np.int64)) if shape else 1
                base = self._by_path[p]
                self._by_path[p] = (
                    base.astype(np.float32)
                    + delta[off:off + n].reshape(shape)
                ).astype(base.dtype)
                off += n
        for p, val in raw.items():
            nb = int(np.asarray(val).nbytes)
            down += nb
            if bucket.owner == self.rank:
                up += nb
            self._by_path[p] = np.asarray(val)
        obs = _obs()
        # encode/decode CPU time only — the broadcast rendezvous itself is
        # excluded (matches the metric description and _reduce_quantized)
        obs["quant_encode"].observe(enc_s + time.perf_counter() - t1)
        # uncompressed equivalent: float leaves would ship 4 B/el; raw
        # leaves ship at their actual size either way (no savings there)
        fp32 = sum(int(np.prod(self._by_path[p].shape, dtype=np.int64)) * 4
                   for p in float_paths)
        fp32 += sum(int(self._by_path[p].nbytes) for p in raw_paths)
        obs["quant_saved"].inc(max(fp32 - down, 0))
        return up, down

    def shutdown(self):
        self._reducer.shutdown()

"""ray_tpu.collective: collective communication between actors/tasks.

Reference: python/ray/util/collective/collective.py — declare-then-rendezvous
group management (``init_collective_group`` :182, ``create_collective_group``
:222) and ops (``allreduce``..``barrier`` :339-736), re-based on TPU-native
backends (see collective_group.py): XLA over ICI/DCN, and a CPU store-actor
tier for CI.

Usage inside an actor::

    from ray_tpu import collective as col
    col.init_collective_group(world_size=4, rank=self.rank, backend="cpu",
                              group_name="grad_sync")
    reduced = col.allreduce(my_array, group_name="grad_sync")
"""

from __future__ import annotations

from typing import Any, List, Optional

from ray_tpu.collective.collective_group import CollectiveStore, CpuStoreGroup, XlaGroup
from ray_tpu.collective.types import Backend, GroupInfo, ReduceOp

__all__ = [
    "init_collective_group",
    "create_collective_group",
    "destroy_collective_group",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "reduce",
    "broadcast",
    "allgather",
    "reducescatter",
    "alltoall",
    "send",
    "recv",
    "barrier",
    "ReduceOp",
    "Backend",
    "get_group",
    "allreduce_quantized",
    # bucketed async tier (collective/bucketed.py): lazy attrs below
    "plan_buckets",
    "leaf_meta",
    "BucketPlan",
    "Bucket",
    "AsyncBucketReducer",
    "ShardedBucketOptimizer",
    "init_sharded_optimizer_groups",
    # quantized tier (collective/quant.py): lazy attrs below
    "QuantCodec",
    "QuantizedTensor",
    "ErrorFeedback",
    "resolve_codec",
    "quantize",
    "dequantize",
]

_BUCKETED = ("plan_buckets", "leaf_meta", "BucketPlan", "Bucket",
             "AsyncBucketReducer", "ShardedBucketOptimizer",
             "init_sharded_optimizer_groups")

_QUANT = ("QuantCodec", "QuantizedTensor", "ErrorFeedback", "resolve_codec",
          "quantize", "dequantize")


def __getattr__(name):  # lazy: bucketed/quant pull numpy/jax helpers
    if name in _BUCKETED:
        from ray_tpu.collective import bucketed

        return getattr(bucketed, name)
    if name in _QUANT:
        from ray_tpu.collective import quant

        return getattr(quant, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class GroupManager:
    """Per-process registry of collective groups (reference: collective.py:84)."""

    def __init__(self):
        self._groups = {}

    def create(self, group_name: str, world_size: int, rank: int, backend: str,
               devices=None):
        backend = Backend.validate(backend)
        if group_name in self._groups:
            raise ValueError(f"collective group {group_name!r} already initialized")
        if backend == Backend.CPU:
            group = CpuStoreGroup(group_name, world_size, rank)
        else:
            group = XlaGroup(group_name, world_size, rank, devices=devices)
        self._groups[group_name] = group
        return group

    def get(self, group_name: str):
        group = self._groups.get(group_name)
        if group is None:
            raise ValueError(
                f"collective group {group_name!r} is not initialized in this "
                f"process; call init_collective_group first")
        return group

    def destroy(self, group_name: str):
        group = self._groups.pop(group_name, None)
        if group is not None:
            group.destroy()


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int, backend: str = Backend.CPU,
                          group_name: str = "default", devices=None):
    """Declare this process/actor as `rank` of a collective group."""
    return _manager.create(group_name, world_size, rank, backend, devices=devices)


def create_collective_group(actors: List[Any], world_size: int, ranks: List[int],
                            backend: str = Backend.CPU, group_name: str = "default"):
    """Driver-side declaration for a set of actors (reference:
    collective.py:222): tells each actor to init its side of the group."""
    import ray_tpu

    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must have equal length")
    refs = [
        actor._init_collective.remote(world_size, rank, backend, group_name)
        for actor, rank in zip(actors, ranks)
    ]
    ray_tpu.get(refs, timeout=300)


def destroy_collective_group(group_name: str = "default"):
    _manager.destroy(group_name)


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


def get_group(group_name: str = "default"):
    """The initialized group object itself (backend-specific ops like
    ``allreduce_quantized`` / ``broadcast_obj`` live on it)."""
    return _manager.get(group_name)


def allreduce_quantized(wire: dict, codec, group_name: str = "default") -> dict:
    """Quantized-SUM allreduce of an encoded contribution (see
    ``collective/quant.py``); CPU backend only — the XLA tier quantizes
    inside compiled programs."""
    return _manager.get(group_name).allreduce_quantized(wire, codec)


def allreduce(tensor, op: ReduceOp = ReduceOp.SUM, group_name: str = "default"):
    return _manager.get(group_name).allreduce(tensor, op)


def reduce(tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM,
           group_name: str = "default"):
    return _manager.get(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _manager.get(group_name).broadcast(tensor, src_rank)


def allgather(tensor, group_name: str = "default"):
    return _manager.get(group_name).allgather(tensor)


def reducescatter(tensor, op: ReduceOp = ReduceOp.SUM, group_name: str = "default"):
    return _manager.get(group_name).reducescatter(tensor, op)


def alltoall(tensor, group_name: str = "default"):
    return _manager.get(group_name).alltoall(tensor)


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    return _manager.get(group_name).send(tensor, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    return _manager.get(group_name).recv(src_rank, tag)


def barrier(group_name: str = "default"):
    return _manager.get(group_name).barrier()


class CollectiveActorMixin:
    """Mixin giving actors the `_init_collective` hook used by
    create_collective_group."""

    def _init_collective(self, world_size: int, rank: int, backend: str,
                         group_name: str):
        init_collective_group(world_size, rank, backend, group_name)
        return True

"""Block-quantized codecs for the compression tier (EQuARX,
arxiv.org/pdf/2506.17615).

A quantized payload is ``(codes: uint8, scales: float32)`` over fixed-size
blocks of the flattened input:

- ``int8``  — symmetric per-block scaling to [-127, 127]; 1 byte/element
  + 4/block bytes of scales (~3.94x smaller than fp32 at block=256).
- ``fp8``   — e4m3 emulation via ``ml_dtypes.float8_e4m3fn`` (the numpy
  dtype jax itself depends on): per-block scaling maps the block amax to
  the e4m3 max (448), then a saturating cast; 1 byte/element.
- ``bf16``  — a plain dtype narrowing (no scales); 2 bytes/element. Not a
  block codec, but resolving here lets ``grad_dtype="bf16"`` ride the same
  wire plumbing as the quantized tiers.

The codecs are **pure numpy** so the CollectiveStore actor (the CPU-tier
reduce point) can dequant-accumulate without importing jax; a jitted
quantize→all_to_all→dequant reduce-scatter for on-device (ICI) byte
reduction lives in :func:`quantized_psum_scatter_1d`.

Error feedback (:class:`ErrorFeedback`): quantization error is *carried*,
not lost — the caller adds the residual before encoding and stores
``compensated - dequant(encode(compensated))`` for the next step, which is
what keeps quantized SGD/adam trajectories near the fp32 one (the
convergence test pins PPO int8 within 2% of fp32).

Non-finite inputs: scales are always finite — NaN entries encode as 0 and
±inf entries saturate to the block's finite amax (a gradient containing
them is already broken; the codec must not poison the whole block's scale,
and a NaN scale would corrupt every element of the block on decode).

When NOT to quantize (see collective/QUANT.md): normalization statistics
and other few-float control values (quantization error is O(value) while
the payload is already tiny), momentum-free accumulators that feed
comparisons, and any leg whose consumer needs bitwise determinism across
code versions. Compression is strictly opt-in everywhere.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

FP8_MAX = 448.0  # ml_dtypes.float8_e4m3fn finite max
DEFAULT_BLOCK = 256

_CODEC_NAMES = ("int8", "fp8", "bf16")


@dataclass(frozen=True)
class QuantCodec:
    """One codec choice: name + block size (block ignored for bf16)."""

    name: str
    block: int = DEFAULT_BLOCK

    def __post_init__(self):
        if self.name not in _CODEC_NAMES:
            raise ValueError(
                f"unknown codec {self.name!r} (one of {_CODEC_NAMES})")
        if self.block <= 0:
            raise ValueError(f"block must be positive, got {self.block}")

    @property
    def bytes_per_element(self) -> float:
        if self.name == "bf16":
            return 2.0
        return 1.0 + 4.0 / self.block  # codes + fp32 scale share

    def spec(self) -> str:
        return f"{self.name}:{self.block}"


def resolve_codec(compression: Any) -> Optional[QuantCodec]:
    """Normalize a user-facing ``compression`` knob into a codec.

    Accepts None / "none" (off), "int8" / "fp8" / "bf16", an
    "int8:128"-style spec with an explicit block size, or a QuantCodec.
    """
    if compression is None:
        return None
    if isinstance(compression, QuantCodec):
        return compression
    if not isinstance(compression, str):
        raise TypeError(f"compression must be a string or QuantCodec, "
                        f"got {type(compression).__name__}")
    s = compression.strip().lower()
    if s in ("", "none", "off", "fp32"):
        return None
    if ":" in s:
        name, _, block = s.partition(":")
        return QuantCodec(name, int(block))
    return QuantCodec(s)


@dataclass
class QuantizedTensor:
    """One encoded array: flat uint8 codes + per-block fp32 scales."""

    codec: str
    block: int
    shape: Tuple[int, ...]
    dtype: str  # original dtype str (decode target)
    codes: np.ndarray  # uint8, flat (padded to a whole number of blocks)
    scales: np.ndarray  # float32, one per block (empty for bf16)

    @property
    def wire_nbytes(self) -> int:
        return int(self.codes.nbytes + self.scales.nbytes)

    @property
    def raw_nbytes(self) -> int:
        n = int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1
        return n * np.dtype(self.dtype).itemsize

    def meta(self) -> Dict[str, Any]:
        return {"codec": self.codec, "block": self.block,
                "shape": list(self.shape), "dtype": self.dtype,
                "nscales": int(self.scales.size)}


def _sanitize_blocks(xb: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Finite-safe (values, amax): NaN -> 0; ±inf saturates to the finite
    amax of its block so one bad element cannot blow up the block scale."""
    finite = np.isfinite(xb)
    if finite.all():
        return xb, np.abs(xb).max(axis=-1)
    xf = np.where(finite, xb, np.float32(0.0))
    amax = np.abs(xf).max(axis=-1)
    cap = np.where(amax > 0, amax, np.float32(1.0))[..., None]
    xf = np.where(np.isnan(xb), np.float32(0.0),
                  np.clip(xb, -cap, cap)).astype(np.float32)
    return xf, np.abs(xf).max(axis=-1)


def _to_blocks(arr: np.ndarray, block: int) -> Tuple[np.ndarray, int]:
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
    n = flat.size
    nb = max(1, -(-n // block))
    padded = np.zeros(nb * block, np.float32)
    padded[:n] = flat
    return padded.reshape(nb, block), n


def quantize(arr: np.ndarray, codec: QuantCodec) -> QuantizedTensor:
    """Encode ``arr`` (any shape, float dtype) into flat uint8 + scales."""
    arr = np.asarray(arr)
    shape, dtype = tuple(arr.shape), arr.dtype.str
    if codec.name == "bf16":
        import ml_dtypes

        codes = np.ascontiguousarray(
            arr.astype(ml_dtypes.bfloat16)).reshape(-1).view(np.uint8)
        return QuantizedTensor(codec.name, codec.block, shape, dtype,
                               codes, np.zeros(0, np.float32))
    xb, n = _to_blocks(arr, codec.block)
    xb, amax = _sanitize_blocks(xb)
    if codec.name == "int8":
        scales = np.where(amax > 0, amax / np.float32(127.0),
                          np.float32(1.0)).astype(np.float32)
        q = np.clip(np.rint(xb / scales[:, None]), -127, 127).astype(np.int8)
        codes = q.reshape(-1).view(np.uint8)
    else:  # fp8 (e4m3 emulation)
        import ml_dtypes

        scales = np.where(amax > 0, amax / np.float32(FP8_MAX),
                          np.float32(1.0)).astype(np.float32)
        y = (xb / scales[:, None]).astype(np.float32)
        # e4m3fn overflows to NaN above the finite max: clamp first (the
        # scale maps amax exactly to FP8_MAX, but fp32 division can land
        # one ulp above it)
        y = np.clip(y, -FP8_MAX, FP8_MAX)
        codes = np.ascontiguousarray(
            y.astype(ml_dtypes.float8_e4m3fn)).reshape(-1).view(np.uint8)
    # the ragged tail's block padding never crosses the wire (codes are
    # 1 byte/element, so truncation at n is exact; decode re-pads)
    return QuantizedTensor(codec.name, codec.block, shape, dtype,
                           np.ascontiguousarray(codes[:n]), scales)


def dequantize(qt: QuantizedTensor) -> np.ndarray:
    """Decode back to the original shape/dtype (lossy)."""
    n = int(np.prod(qt.shape, dtype=np.int64)) if qt.shape else 1
    if qt.codec == "bf16":
        import ml_dtypes

        vals = qt.codes.view(ml_dtypes.bfloat16).astype(np.float32)
        return vals[:n].reshape(qt.shape).astype(np.dtype(qt.dtype))
    nb = qt.scales.size
    codes = qt.codes
    if codes.size < nb * qt.block:  # re-pad the truncated ragged tail
        codes = np.concatenate(
            [codes, np.zeros(nb * qt.block - codes.size, np.uint8)])
    if qt.codec == "int8":
        q = codes.view(np.int8).astype(np.float32)
    else:
        import ml_dtypes

        q = codes.view(ml_dtypes.float8_e4m3fn).astype(np.float32)
    vals = (q.reshape(nb, -1) * qt.scales[:, None]).reshape(-1)
    return vals[:n].reshape(qt.shape).astype(np.dtype(qt.dtype))


# -- single-buffer wire form (weight-plane chunks) --------------------------


def encode_array(arr: np.ndarray, codec: QuantCodec
                 ) -> Tuple[np.ndarray, Dict[str, Any]]:
    """Encode into ONE flat uint8 buffer ``[scales fp32 | codes]`` plus a
    JSON-safe meta dict — the weight-store chunk encoding (the manifest
    records ``enc``; pulls decode transparently)."""
    qt = quantize(arr, codec)
    wire = np.empty(qt.scales.nbytes + qt.codes.nbytes, np.uint8)
    wire[:qt.scales.nbytes] = qt.scales.view(np.uint8)
    wire[qt.scales.nbytes:] = qt.codes
    return wire, qt.meta()


def decode_array(wire: np.ndarray, meta: Dict[str, Any]) -> np.ndarray:
    wire = np.asarray(wire, dtype=np.uint8).reshape(-1)
    nscales = int(meta["nscales"])
    scales = wire[:nscales * 4].view(np.float32).copy()
    codes = wire[nscales * 4:].copy()
    return dequantize(QuantizedTensor(
        meta["codec"], int(meta["block"]), tuple(meta["shape"]),
        meta["dtype"], codes, scales))


# -- actor-wire form (collective payloads; arrays ride out-of-band) ---------


def to_wire(qt: QuantizedTensor, extra: Optional[np.ndarray] = None
            ) -> Dict[str, Any]:
    """``extra`` is an optional small fp32 vector (metrics / control
    scalars) that rides the same exchange UNQUANTIZED and is summed
    exactly at the reduce point — one collective round trip instead of
    two, without quantizing the few-float leg (see "when NOT to
    quantize")."""
    d = {"codec": qt.codec, "block": qt.block, "shape": list(qt.shape),
         "dtype": qt.dtype, "codes": qt.codes, "scales": qt.scales}
    if extra is not None:
        d["extra"] = np.asarray(extra, np.float32)
    return d


def from_wire(d: Dict[str, Any]) -> QuantizedTensor:
    return QuantizedTensor(d["codec"], int(d["block"]),
                           tuple(d["shape"]), d["dtype"],
                           np.asarray(d["codes"], np.uint8),
                           np.asarray(d["scales"], np.float32))


def wire_nbytes(d: Dict[str, Any]) -> int:
    return int(np.asarray(d["codes"]).nbytes
               + np.asarray(d["scales"]).nbytes)


# -- error feedback ---------------------------------------------------------


class ErrorFeedback:
    """Per-key residual accumulator: quantization error is carried into
    the next step's contribution instead of lost.

    ``encode(key, arr)`` returns ``quantize(arr + residual[key])`` and
    stores the new residual. Keys are caller-chosen (bucket index, dtype,
    leg) and residuals are local — never synchronized."""

    def __init__(self, codec: QuantCodec):
        self.codec = codec
        self._residual: Dict[Any, np.ndarray] = {}

    def encode(self, key: Any, arr: np.ndarray) -> QuantizedTensor:
        x = np.asarray(arr, np.float32)
        res = self._residual.get(key)
        if res is not None and res.shape == x.shape:
            x = x + res
        qt = quantize(x, self.codec)
        self._residual[key] = (x - dequantize(qt).astype(np.float32)
                               ).reshape(x.shape)
        return qt

    def residual_norm(self, key: Any) -> float:
        res = self._residual.get(key)
        return 0.0 if res is None else float(np.linalg.norm(res))

    def reset(self):
        self._residual.clear()


# -- store-side reduce (dequant-accumulate fp32, requantize once) -----------


def reduce_wire_payloads(payloads, codec_spec: str) -> Dict[str, Any]:
    """The reduce point of the quantized collective: dequantize every
    rank's contribution, accumulate in fp32, and re-quantize ONCE for the
    broadcast leg. Runs inside the CollectiveStore actor (pure numpy)."""
    name, _, block = codec_spec.partition(":")
    codec = QuantCodec(name, int(block) if block else DEFAULT_BLOCK)
    total: Optional[np.ndarray] = None
    extra: Optional[np.ndarray] = None
    for p in payloads:
        val = dequantize(from_wire(p)).astype(np.float32)
        total = val if total is None else total + val
        if p.get("extra") is not None:
            e = np.asarray(p["extra"], np.float32)
            extra = e if extra is None else extra + e
    return to_wire(quantize(total, codec), extra=extra)


# -- XLA tier: jitted quantize -> all_to_all -> dequant reduce-scatter ------


def jnp_block_encode(xb, codec_name: str):
    """Traced (jnp) flavor of the block encode — the ONE home for the
    quantization math shared by every XLA-tier program
    (:func:`quantized_psum_scatter_1d` below and the TrainStepBundle
    per-bucket reduce-scatter). ``xb`` is ``(..., nblocks, block)`` fp32;
    returns ``(codes, scales)`` with scales shaped ``(..., nblocks)``."""
    import jax.numpy as jnp

    # same finite-safe contract as the numpy _sanitize_blocks: NaN -> 0,
    # ±inf saturates to the block's finite amax — one overflowed element
    # must not turn the block scale (and thus all `block` decoded values)
    # into inf/NaN. Unconditional (no finite.all() fast path inside a
    # traced program).
    finite = jnp.isfinite(xb)
    xf = jnp.where(finite, xb, 0.0)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    cap = jnp.where(amax > 0, amax, 1.0)[..., None]
    xb = jnp.where(jnp.isnan(xb), 0.0, jnp.clip(xb, -cap, cap))
    if codec_name == "int8":
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(xb / scale[..., None]),
                     -127, 127).astype(jnp.int8)
    else:  # fp8: clamp BEFORE the saturating cast — e4m3fn overflows to
        # NaN above the finite max, and the fp32 division can land one
        # ulp above it even though the scale maps amax to FP8_MAX exactly
        scale = jnp.where(amax > 0, amax / FP8_MAX, 1.0)
        q = jnp.clip(xb / scale[..., None], -FP8_MAX,
                     FP8_MAX).astype(jnp.float8_e4m3fn)
    return q, scale


def quantized_psum_scatter_1d(mesh, axis_name: str, codec: QuantCodec):
    """Build a jitted shard_map program computing ``psum_scatter`` of a
    flat fp32 vector with int8/fp8 bytes on the wire.

    Decomposition (the standard quantized-allreduce reduce-scatter leg):
    each device splits its local vector into N per-owner segments,
    block-quantizes each segment, ``all_to_all``s the uint8 codes + fp32
    scales (THE wire leg — 1 byte/element instead of 4), then
    dequant-accumulates its own segment in fp32. Output = this device's
    tiled segment of the sum, exactly ``psum_scatter(..., tiled=True)``
    semantics (to quantization error).

    The local vector length must be divisible by ``N`` (callers pad);
    block padding is internal (static shapes — the pad amount folds into
    the program). Returns ``fn(local_vec) -> owned_segment``.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n = int(np.prod([s for nme, s in zip(mesh.axis_names, mesh.devices.shape)
                     if nme == axis_name]))
    block = codec.block
    if codec.name == "bf16":
        def f(x):
            seg = x.reshape(n, -1).astype(jnp.bfloat16)  # wire dtype
            mine = jax.lax.all_to_all(seg, axis_name, split_axis=0,
                                      concat_axis=0, tiled=False)
            return jnp.sum(mine.astype(jnp.float32), axis=0)
    else:
        def f(x):
            seg_len = x.shape[0] // n
            nb = -(-seg_len // block)
            pad = nb * block - seg_len
            seg = x.reshape(n, seg_len)
            if pad:
                seg = jnp.pad(seg, ((0, 0), (0, pad)))
            seg = seg.reshape(n, nb, block)
            q, scale = jnp_block_encode(seg, codec.name)
            # THE wire leg: 1-byte codes + per-block scales cross devices
            qg = jax.lax.all_to_all(q, axis_name, split_axis=0,
                                    concat_axis=0, tiled=False)
            sg = jax.lax.all_to_all(scale, axis_name, split_axis=0,
                                    concat_axis=0, tiled=False)
            vals = qg.astype(jnp.float32) * sg[..., None]
            return jnp.sum(vals, axis=0).reshape(-1)[:seg_len]

    return jax.jit(shard_map(f, mesh=mesh, in_specs=P(axis_name),
                             out_specs=P(axis_name), check_rep=False))


def xla_wire_bytes(n_elements: int, world: int, codec: Optional[QuantCodec]
                   ) -> int:
    """Per-device wire bytes of one reduce-scatter leg over ``n_elements``
    (the (N-1)/N share that actually crosses links; fp32 when codec is
    None). Analytic — CPU-emulated meshes have no byte counters."""
    frac = (world - 1) / max(world, 1)
    per = 4.0 if codec is None else codec.bytes_per_element
    return int(n_elements * per * frac)

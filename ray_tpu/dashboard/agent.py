"""Per-node agent: process/node stats + worker profiling.

Reference: python/ray/dashboard/agent.py:23 + modules/reporter/ — the
reference runs one agent process per node that samples every worker's
cpu/rss via psutil, reports to the dashboard, and serves profiling requests
(py-spy stack sampling, memray allocation tracking). Here the agent is a
component hosted by the raylet (one fewer process per node, same surface):
``collect()`` backs the extended ``GetNodeStats`` RPC, and the profiling
half lives in every worker as RPC handlers (``ProfileStacks`` /
``ProfileMemory``) backed by :func:`sample_stacks` — a cooperative
stack-sampling profiler (sys._current_frames) and tracemalloc, the
pure-Python equivalents of py-spy / memray that need no ptrace.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional


class NodeAgent:
    """Collects node + per-worker process stats (reference:
    dashboard/modules/reporter/reporter_agent.py)."""

    def __init__(self):
        self._boot = time.time()
        self._procs: Dict[int, object] = {}  # pid -> psutil.Process

    def collect(self, worker_pids: List[int]) -> dict:
        import psutil

        vm = psutil.virtual_memory()
        try:
            load1, load5, load15 = os.getloadavg()
        except OSError:
            load1 = load5 = load15 = 0.0
        disk = psutil.disk_usage("/")
        workers = []
        seen = set()
        for pid in worker_pids:
            seen.add(pid)
            try:
                proc = self._procs.get(pid)
                if proc is None:
                    proc = psutil.Process(pid)
                    proc.cpu_percent(interval=None)  # prime the counter
                    self._procs[pid] = proc
                with proc.oneshot():
                    workers.append({
                        "pid": pid,
                        "cpu_percent": proc.cpu_percent(interval=None),
                        "rss_mb": round(proc.memory_info().rss / 2**20, 1),
                        "num_fds": proc.num_fds(),
                        "num_threads": proc.num_threads(),
                        "create_time": proc.create_time(),
                    })
            except Exception:
                continue  # worker exited between listing and sampling
        for pid in list(self._procs):
            if pid not in seen:
                del self._procs[pid]
        return {
            "uptime_s": round(time.time() - self._boot, 1),
            "cpu_percent": psutil.cpu_percent(interval=None),
            "mem_total_mb": round(vm.total / 2**20, 1),
            "mem_available_mb": round(vm.available / 2**20, 1),
            "mem_percent": vm.percent,
            "load_avg": [load1, load5, load15],
            "disk_percent": disk.percent,
            "workers": workers,
        }


def sample_stacks(duration_s: float = 2.0, interval_ms: float = 10.0,
                  target_thread_ids: Optional[List[int]] = None) -> dict:
    """In-process stack-sampling profiler (the py-spy role, cooperatively).

    A sampler thread snapshots ``sys._current_frames()`` every
    ``interval_ms`` for ``duration_s`` and aggregates frames into folded
    stacks ("a;b;c count" — the flamegraph input format py-spy emits with
    --format raw). The sampler excludes itself.
    """
    import sys

    folded: Dict[str, int] = {}
    samples = 0
    stop = time.monotonic() + max(0.05, duration_s)
    me = threading.get_ident()
    interval = max(0.001, interval_ms / 1000.0)
    while time.monotonic() < stop:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            if target_thread_ids and tid not in target_thread_ids:
                continue
            stack = []
            f = frame
            depth = 0
            while f is not None and depth < 64:
                code = f.f_code
                stack.append(f"{os.path.basename(code.co_filename)}:"
                             f"{code.co_name}:{f.f_lineno}")
                f = f.f_back
                depth += 1
            key = ";".join(reversed(stack))
            folded[key] = folded.get(key, 0) + 1
        samples += 1
        time.sleep(interval)
    top = sorted(folded.items(), key=lambda kv: -kv[1])
    return {
        "samples": samples,
        "duration_s": duration_s,
        "folded": dict(top[:500]),
        "top": [{"stack": k.rsplit(";", 3)[-1], "count": v}
                for k, v in top[:25]],
    }


class MemoryProfiler:
    """tracemalloc wrapper (the memray role, allocation tracking)."""

    def __init__(self):
        self._running = False

    def start(self, frames: int = 16):
        import tracemalloc

        if not self._running:
            tracemalloc.start(frames)
            self._running = True
        return {"status": "started"}

    def snapshot(self, top: int = 25) -> dict:
        import tracemalloc

        if not self._running:
            return {"status": "not_running", "top": []}
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("traceback")
        out = []
        for st in stats[:top]:
            out.append({
                "size_kb": round(st.size / 1024, 1),
                "count": st.count,
                "traceback": [str(fr) for fr in st.traceback.format()[-6:]],
            })
        current, peak = tracemalloc.get_traced_memory()
        return {"status": "ok", "current_kb": round(current / 1024, 1),
                "peak_kb": round(peak / 1024, 1), "top": out}

    def stop(self):
        import tracemalloc

        if self._running:
            tracemalloc.stop()
            self._running = False
        return {"status": "stopped"}

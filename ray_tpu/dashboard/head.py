"""Dashboard-lite: the head HTTP server.

Reference: python/ray/dashboard/head.py (:49) + its modules — state
(``modules/state``), jobs REST (``modules/job/job_head.py``), Prometheus
metrics (``modules/metrics``), logs (``modules/log``). This build serves the
same surfaces from one aiohttp app backed directly by the GCS (no React
frontend; a minimal HTML status page instead).

Endpoints:
  GET  /                     - HTML cluster overview
  GET  /api/version          - framework version
  GET  /api/state            - full GCS state dump
  GET  /api/nodes|actors|pgs - tables
  GET  /api/cluster_status   - autoscaler view (demands, idle, per-node)
  GET  /api/summary          - aggregate counts
  GET  /api/workers          - per-node worker-pool / provisioning stats
  GET  /api/timeline         - Perfetto chrome-trace of the task flow graph
  GET  /api/health           - cluster-health report (stuck/straggler scan)
  GET  /api/goodput          - per-job goodput ledgers (wall-clock buckets)
  GET  /api/metrics/history  - metric time-series (raw + rollup tiers)
  GET  /metrics              - Prometheus text exposition
  GET  /api/jobs             - submitted jobs (job manager KV)
  POST /api/jobs             - {"entrypoint": ..., "runtime_env": ...}
  GET  /api/jobs/{id}        - job info
  GET  /api/jobs/{id}/logs   - job logs (text)
  POST /api/jobs/{id}/stop   - stop a job
"""

from __future__ import annotations

import asyncio
import json
from ray_tpu._private import wire
import time
from typing import Optional

from ray_tpu._private.rpc import RetryingRpcClient

_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body {{ font-family: monospace; margin: 2em; background: #111; color: #eee; }}
 h1 {{ color: #7fd4ff; }} table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #444; padding: 4px 10px; text-align: left; }}
 a {{ color: #7fd4ff; }}
</style></head>
<body>
<h1>ray_tpu cluster</h1>
<p>uptime {uptime:.0f}s &middot; {num_nodes} nodes &middot; {num_actors} actors
&middot; {num_jobs} jobs</p>
<h2>resources</h2><table>{resources}</table>
<h2>nodes</h2><table><tr><th>node</th><th>alive</th><th>resources</th>
<th>labels</th></tr>{nodes}</table>
<h2>actors</h2><table><tr><th>actor</th><th>class</th><th>state</th>
<th>name</th></tr>{actors}</table>
<p><a href="/api/state">/api/state</a> &middot;
<a href="/api/cluster_status">/api/cluster_status</a> &middot;
<a href="/metrics">/metrics</a> &middot; <a href="/api/jobs">/api/jobs</a></p>
</body></html>"""


class DashboardHead:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1", port: int = 0):
        self.gcs_address = gcs_address
        self.host = host
        self.port = port
        self._gcs: Optional[RetryingRpcClient] = None
        self._runner = None
        self._site = None

    # -- GCS I/O -------------------------------------------------------

    async def _call(self, method: str, req: dict) -> dict:
        if self._gcs is None:
            self._gcs = RetryingRpcClient(self.gcs_address)
        return wire.loads(await self._gcs.call(method, wire.dumps(req)))

    # -- lifecycle -----------------------------------------------------

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        app.add_routes([
            web.get("/", self._index),
            web.get("/api/version", self._version),
            web.get("/api/state", self._state),
            web.get("/api/nodes", self._nodes),
            web.get("/api/actors", self._actors),
            web.get("/api/pgs", self._pgs),
            web.get("/api/cluster_status", self._cluster_status),
            web.get("/api/summary", self._summary),
            web.get("/api/tasks", self._tasks),
            web.get("/api/tasks/summary", self._tasks_summary),
            web.get("/api/timeline", self._timeline),
            web.get("/api/health", self._health),
            web.get("/api/goodput", self._goodput),
            web.get("/api/metrics/history", self._metrics_history),
            web.get("/api/workers", self._workers),
            web.get("/metrics", self._prometheus),
            web.get("/api/nodes/{node_id}/stats", self._node_stats),
            web.get("/api/data_stats", self._data_stats),
            web.get("/api/weights", self._weights),
            web.get("/api/checkpoints", self._checkpoints),
            web.get("/api/serve", self._serve),
            web.post("/api/profile/stacks", self._profile_stacks),
            web.post("/api/profile/memory", self._profile_memory),
            web.get("/api/jobs", self._jobs_list),
            web.post("/api/jobs", self._jobs_submit),
            web.get("/api/jobs/{id}", self._job_info),
            web.get("/api/jobs/{id}/logs", self._job_logs),
            web.post("/api/jobs/{id}/stop", self._job_stop),
        ])
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()
        self.port = self._site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        if self._runner:
            await self._runner.cleanup()
        if self._gcs:
            await self._gcs.close()

    # -- handlers ------------------------------------------------------

    async def _version(self, request):
        from aiohttp import web

        import ray_tpu

        return web.json_response({"version": getattr(ray_tpu, "__version__", "dev"),
                                  "gcs_address": self.gcs_address})

    async def _state(self, request):
        from aiohttp import web

        return web.json_response(await self._call("GetState", {}))

    async def _nodes(self, request):
        from aiohttp import web

        return web.json_response(
            (await self._call("GetAllNodes", {}))["nodes"])

    async def _actors(self, request):
        from aiohttp import web

        return web.json_response(
            (await self._call("ListActors", {}))["actors"])

    async def _raylet(self, node_id: str):
        """Cached client to one node's raylet (for agent stats/profiling)."""
        if not hasattr(self, "_raylets"):
            self._raylets = {}
        client = self._raylets.get(node_id)
        if client is None:
            nodes = (await self._call("GetAllNodes", {}))["nodes"]
            addr = next((n["address"] for n in nodes
                         if n["node_id"].startswith(node_id) and n["alive"]),
                        None)
            if addr is None:
                return None
            client = RetryingRpcClient(addr)
            self._raylets[node_id] = client
        return client

    async def _data_stats(self, request):
        """Recent Dataset executions' per-op metrics (reference: the data
        tab of the dashboard; fed by Dataset._publish_stats)."""
        from aiohttp import web

        keys = (await self._call("KVKeys",
                                 {"ns": "data_stats", "prefix": ""}))["keys"]
        out = []
        for k in keys[-50:]:
            blob = (await self._call("KVGet",
                                     {"ns": "data_stats", "key": k}))["value"]
            if blob is not None:
                entry = wire.loads(blob)
                entry["dataset"] = k
                out.append(entry)
        out.sort(key=lambda e: e.get("ts", 0))
        return web.json_response(out)

    async def _kv_namespace_dump(self, ns: str) -> dict:
        """All wire-decoded values of one stats-mirror KV namespace
        (one batched KVMultiGet instead of a round trip per key)."""
        keys = (await self._call("KVKeys", {"ns": ns, "prefix": ""}))["keys"]
        values = (await self._call("KVMultiGet",
                                   {"ns": ns, "keys": keys}))["values"]
        return {k: wire.loads(blob) for k, blob in values.items()
                if blob is not None}

    async def _workers(self, request):
        """Per-node worker-pool stats from the provisioning plane: warm
        pool size, zygote liveness, adoption hit/miss and fork/cold-spawn
        counters (mirrored to the ``workers`` KV namespace by every
        raylet's metrics loop)."""
        from aiohttp import web

        per_node = await self._kv_namespace_dump("workers")
        totals = {"hits": 0, "misses": 0, "forks": 0, "cold_spawns": 0,
                  "zygote_restarts": 0, "total_workers": 0,
                  "warm_default_env": 0}
        for entry in per_node.values():
            pool = entry.get("pool", {})
            for k in totals:
                totals[k] += int(pool.get(k, 0) or 0)
        return web.json_response({"nodes": per_node, "totals": totals})

    async def _weights(self, request):
        """Weight-plane stores: per-version publish/pull bytes, chunk
        counts, commit timestamps (mirrored to the ``weights`` KV namespace
        by WeightStoreActor on every commit/pull)."""
        from aiohttp import web

        return web.json_response(await self._kv_namespace_dump("weights"))

    async def _checkpoints(self, request):
        """Checkpoint-plane stores: per-store latest/pinned ids, per-
        checkpoint step/bytes/dedup stats, retention drop counters and —
        for tiered stores — per-checkpoint residency columns plus the
        latest GCS sweeper report (``ckpt`` / ``ckpt_sweep`` KV
        namespaces, mirrored by CheckpointStore/TieredStore and the
        retention sweeper)."""
        from aiohttp import web

        stores = await self._kv_namespace_dump("ckpt")
        sweeps = await self._kv_namespace_dump("ckpt_sweep")
        for name, stats in stores.items():
            if isinstance(stats, dict) and name in sweeps:
                stats["last_sweep"] = sweeps[name]
        return web.json_response(stores)

    async def _serve(self, request):
        """Serve autoscale plane: per-deployment replica target vs live
        count, windowed rate rollup (arrival rate, queue p99, execute
        mean), registered SLOs and recent scale transitions (mirrored to
        the ``serve`` KV namespace by the controller every autoscale
        tick)."""
        from aiohttp import web

        return web.json_response(await self._kv_namespace_dump("serve"))

    async def _node_stats(self, request):
        """Per-node agent sample: node cpu/mem/load + every worker's
        cpu/rss/fds (reference: dashboard modules/reporter)."""
        from aiohttp import web

        client = await self._raylet(request.match_info["node_id"])
        if client is None:
            return web.json_response({"error": "unknown node"}, status=404)
        stats = wire.loads(await client.call(
            "GetNodeStats", wire.dumps({"agent": True}), timeout=30.0))
        return web.json_response(stats)

    async def _profile(self, request, kind: str):
        from aiohttp import web

        body = await request.json()
        client = await self._raylet(str(body.get("node_id", "")))
        if client is None:
            return web.json_response({"error": "unknown node"}, status=404)
        out = wire.loads(await client.call("ProfileWorker", wire.dumps({
            "pid": int(body["pid"]), "kind": kind,
            "args": body.get("args") or {},
            "timeout": float(body.get("timeout", 60.0)),
        }), timeout=float(body.get("timeout", 60.0)) + 10.0))
        status = 200 if out.get("status") == "ok" else 404
        return web.json_response(out, status=status)

    async def _profile_stacks(self, request):
        return await self._profile(request, "stacks")

    async def _profile_memory(self, request):
        return await self._profile(request, "memory")

    async def _tasks(self, request):
        """Task lifecycle records from the GCS task manager (reference:
        the dashboard's state API /api/v0/tasks). Query params: job_id,
        name, state, limit."""
        from aiohttp import web

        q = request.query
        reply = await self._call("ListTasks", {
            "job_id": q.get("job_id"), "name": q.get("name"),
            "state": q.get("state"), "limit": int(q.get("limit", 200))})
        return web.json_response(reply["tasks"])

    async def _tasks_summary(self, request):
        """Per-function counts by lifecycle state (`ray summary tasks`)."""
        from aiohttp import web

        return web.json_response(await self._call(
            "SummarizeTasks", {"job_id": request.query.get("job_id")}))

    async def _timeline(self, request):
        """Perfetto-loadable chrome-trace JSON of the task flow graph from
        the GCS task-event ring (+ built-in spans), filterable by job and
        time window. Query params: job_id, start_ts, end_ts (unix
        seconds), limit, spans=0 to omit span records. Save the body and
        open it in ui.perfetto.dev / chrome://tracing."""
        from aiohttp import web

        q = request.query
        req = {"job_id": q.get("job_id") or None,
               "limit": int(q.get("limit", 5000)),
               "spans": q.get("spans", "1") not in ("0", "false")}
        if q.get("start_ts"):
            req["start_ts"] = float(q["start_ts"])
        if q.get("end_ts"):
            req["end_ts"] = float(q["end_ts"])
        return web.json_response(await self._call("GetTimeline", req))

    async def _health(self, request):
        """Latest cluster-health report (stuck tasks, straggler nodes,
        provisioning-pool pathology). ``?scan=1`` forces a scan NOW
        instead of returning the last periodic one."""
        from aiohttp import web

        scan = request.query.get("scan", "0") not in ("0", "false", "")
        reply = await self._call("GetClusterHealth", {"scan": scan})
        return web.json_response(reply["health"])

    async def _goodput(self, request):
        """Per-job goodput ledgers: cumulative wall-clock attribution
        buckets, counters, and the derived goodput_fraction.
        ``?job=<run name>`` filters to one job."""
        from aiohttp import web

        req = {}
        if request.query.get("job"):
            req["job"] = request.query["job"]
        reply = await self._call("GetGoodput", req)
        return web.json_response(reply["jobs"])

    async def _metrics_history(self, request):
        """Metric time-series from the GCS history ring. Query params:
        name (omit to list recorded names), window (seconds),
        tier=raw|rollup|auto."""
        from aiohttp import web

        q = request.query
        name = q.get("name")
        if not name:
            return web.json_response(
                (await self._call("GetMetricsHistory", {}))["names"])
        req = {"name": name, "tier": q.get("tier") or "auto"}
        if q.get("window"):
            req["window_s"] = float(q["window"])
        reply = await self._call("GetMetricsHistory", req)
        return web.json_response(reply["history"])

    async def _pgs(self, request):
        from aiohttp import web

        return web.json_response((await self._call("GetState", {}))["pgs"])

    async def _cluster_status(self, request):
        from aiohttp import web

        return web.json_response(await self._call("GetClusterStatus", {}))

    async def _summary(self, request):
        from aiohttp import web

        state = await self._call("GetState", {})
        by_state: dict = {}
        for a in state["actors"]:
            by_state[a["state"]] = by_state.get(a["state"], 0) + 1
        return web.json_response({
            "num_nodes": sum(1 for n in state["nodes"] if n["alive"]),
            "num_actors": len(state["actors"]),
            "actors_by_state": by_state,
            "num_jobs": len(state["jobs"]),
            "num_placement_groups": len(state["pgs"]),
            "uptime_s": state.get("uptime_s", 0.0),
        })

    async def _index(self, request):
        from aiohttp import web

        state = await self._call("GetState", {})
        res = await self._call("GetClusterResources", {})
        rows_r = "".join(
            f"<tr><td>{k}</td><td>{res['available'].get(k, 0):g} / {v:g}</td></tr>"
            for k, v in sorted(res["total"].items()))
        rows_n = "".join(
            f"<tr><td>{n['node_id'][:12]}</td><td>{n['alive']}</td>"
            f"<td>{n['total_resources']}</td><td>{n['labels']}</td></tr>"
            for n in state["nodes"])
        rows_a = "".join(
            f"<tr><td>{a['actor_id'][:12]}</td><td>{a['class_name']}</td>"
            f"<td>{a['state']}</td><td>{a['name']}</td></tr>"
            for a in state["actors"][:200])
        html = _HTML.format(
            uptime=state.get("uptime_s", 0.0),
            num_nodes=sum(1 for n in state["nodes"] if n["alive"]),
            num_actors=len(state["actors"]),
            num_jobs=len(state["jobs"]),
            resources=rows_r, nodes=rows_n, actors=rows_a)
        return web.Response(text=html, content_type="text/html")

    # -- Prometheus ----------------------------------------------------

    async def _prometheus(self, request):
        from aiohttp import web

        lines = []

        def emit(name, value, labels=None, help_=None, kind="gauge"):
            if help_:
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {kind}")
            label_s = ""
            if labels:
                inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
                label_s = "{" + inner + "}"
            lines.append(f"{name}{label_s} {value}")

        state = await self._call("GetState", {})
        res = await self._call("GetClusterResources", {})
        emit("ray_tpu_cluster_nodes_alive",
             sum(1 for n in state["nodes"] if n["alive"]),
             help_="alive raylets", kind="gauge")
        first = True
        for k, v in sorted(res["total"].items()):
            emit("ray_tpu_cluster_resource_total", v, {"resource": k},
                 help_="total cluster resources" if first else None)
            first = False
        first = True
        for k, v in sorted(res["available"].items()):
            emit("ray_tpu_cluster_resource_available", v, {"resource": k},
                 help_="available cluster resources" if first else None)
            first = False
        by_state: dict = {}
        for a in state["actors"]:
            by_state[a["state"]] = by_state.get(a["state"], 0) + 1
        first = True
        for st, n in sorted(by_state.items()):
            emit("ray_tpu_actors", n, {"state": st},
                 help_="actors by state" if first else None)
            first = False

        # application metrics published by workers (util/metrics.py)
        keys = (await self._call("KVKeys", {"ns": "metrics", "prefix": ""}))["keys"]
        seen_names = set()
        for key in keys:
            blob = (await self._call("KVGet", {"ns": "metrics", "key": key}))["value"]
            if blob is None:
                continue
            try:
                payload = wire.loads(blob)
            except Exception:
                continue
            if time.time() - payload.get("time", 0) > 120:
                continue  # stale process snapshot
            # pid alone is not unique cluster-wide (two nodes can both have
            # a pid 1234; duplicate label sets make Prometheus reject the
            # whole scrape) — disambiguate with the reporting node
            proc_labels = {"pid": str(payload["pid"])}
            if payload.get("node"):
                proc_labels["node"] = str(payload["node"])[:16]
            for name, m in payload.get("metrics", {}).items():
                prom = name.replace(".", "_").replace("-", "_")
                if m["kind"] in ("counter", "gauge"):
                    for tag_json, val in m["data"].items():
                        labels = {**json.loads(tag_json), **proc_labels}
                        emit(prom, val, labels,
                             help_=m.get("description") if prom not in seen_names else None,
                             kind=m["kind"])
                        seen_names.add(prom)
                elif m["kind"] == "histogram":
                    bounds = m["data"].get("boundaries") or []
                    first_h = prom not in seen_names
                    seen_names.add(prom)
                    if first_h:
                        lines.append(f"# HELP {prom} {m.get('description', '')}")
                        lines.append(f"# TYPE {prom} histogram")
                    for tag_json, counts in m["data"].get("counts", {}).items():
                        labels = {**json.loads(tag_json), **proc_labels}
                        cum = 0
                        for b, c in zip(bounds, counts):
                            cum += c
                            emit(prom + "_bucket", cum, {**labels, "le": str(b)})
                        cum += counts[-1] if len(counts) > len(bounds) else 0
                        emit(prom + "_bucket", cum, {**labels, "le": "+Inf"})
                        emit(prom + "_count", cum, labels)
                        s = m["data"].get("sums", {}).get(tag_json)
                        if s is not None:
                            emit(prom + "_sum", s, labels)
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    # -- jobs ----------------------------------------------------------

    async def _jobs_list(self, request):
        from aiohttp import web

        keys = (await self._call("KVKeys", {"ns": "job", "prefix": ""}))["keys"]
        out = []
        for k in keys:
            blob = (await self._call("KVGet", {"ns": "job", "key": k}))["value"]
            if blob is not None:
                out.append(wire.loads(blob))
        return web.json_response(out)

    async def _job_info(self, request):
        from aiohttp import web

        sid = request.match_info["id"]
        blob = (await self._call("KVGet", {"ns": "job", "key": sid}))["value"]
        if blob is None:
            return web.json_response({"error": f"no job {sid}"}, status=404)
        return web.json_response(wire.loads(blob))

    async def _job_logs(self, request):
        from aiohttp import web

        sid = request.match_info["id"]
        blob = (await self._call("KVGet", {"ns": "job_logs", "key": sid}))["value"]
        return web.Response(text=(blob or b"").decode(errors="replace"),
                            content_type="text/plain")

    async def _jobs_submit(self, request):
        from aiohttp import web

        body = await request.json()
        if "entrypoint" not in body:
            return web.json_response({"error": "entrypoint required"}, status=400)

        def _submit():
            from ray_tpu.job.job_manager import JobSubmissionClient

            client = JobSubmissionClient(self.gcs_address)
            return client.submit_job(
                entrypoint=body["entrypoint"],
                runtime_env=body.get("runtime_env"),
                submission_id=body.get("submission_id"),
                metadata=body.get("metadata"))

        sid = await asyncio.get_event_loop().run_in_executor(None, _submit)
        return web.json_response({"submission_id": sid})

    async def _job_stop(self, request):
        from aiohttp import web

        sid = request.match_info["id"]

        def _stop():
            from ray_tpu.job.job_manager import JobSubmissionClient

            client = JobSubmissionClient(self.gcs_address)
            return client.stop_job(sid)

        ok = await asyncio.get_event_loop().run_in_executor(None, _stop)
        return web.json_response({"stopped": bool(ok)})


def main():
    from ray_tpu._private.common import die_with_parent

    die_with_parent()

    import argparse

    from ray_tpu._private.logs import setup_process_logging

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8265)
    parser.add_argument("--log-dir", default="")
    parser.add_argument("--address-file", default="")
    args = parser.parse_args()
    setup_process_logging("dashboard", args.log_dir)

    async def run():
        head = DashboardHead(args.gcs_address, args.host, args.port)
        port = await head.start()
        if args.address_file:
            import os

            tmp = args.address_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{args.host}:{port}")
            os.replace(tmp, args.address_file)
        print(f"dashboard listening on http://{args.host}:{port}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()

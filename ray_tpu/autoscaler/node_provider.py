"""Cloud node providers (reference: python/ray/autoscaler/node_provider.py
interface + the fake_multi_node test provider that "launches" local
processes — ``autoscaler/_private/fake_multi_node/node_provider.py``).

A provider launches/terminates raw nodes; the raylet on each node registers
itself with the GCS. TPU slice types launch ``hosts_per_slice`` nodes as one
gang with a shared slice-name label (queued-resources semantics: all hosts
of a slice become available together)."""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.config import NodeTypeConfig


@dataclass
class ProviderNode:
    node_id: str
    node_type: str
    created_at: float = field(default_factory=time.time)
    slice_name: str = ""
    # filled by providers that can map provider nodes to raylet node ids
    raylet_node_id: str = ""


class NodeProvider:
    """Interface for cloud plugins (aws/gcp/gke-tpu/... in the reference)."""

    def create_nodes(self, node_type: NodeTypeConfig, count: int) -> List[ProviderNode]:
        raise NotImplementedError

    def terminate_node(self, node: ProviderNode) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[ProviderNode]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Test provider: "launches" nodes as extra raylets of a local
    ``cluster_utils.Cluster`` (one process per fake node)."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._nodes: Dict[str, ProviderNode] = {}
        self._handles: Dict[str, object] = {}
        self._lock = threading.Lock()

    def create_nodes(self, node_type: NodeTypeConfig, count: int) -> List[ProviderNode]:
        out = []
        for _ in range(count):
            slice_name = ""
            labels = dict(node_type.labels)
            gang = 1
            if node_type.is_slice:
                gang = node_type.hosts_per_slice
                slice_name = f"fake-slice-{uuid.uuid4().hex[:6]}"
                labels[node_type.slice_label_key] = slice_name
            for _h in range(gang):
                node = ProviderNode(
                    node_id=f"fake-{uuid.uuid4().hex[:8]}",
                    node_type=node_type.name,
                    slice_name=slice_name,
                )
                # the provider-id label is the join key the reconciler uses
                # to match GCS nodes to provider nodes
                host_labels = dict(labels)
                host_labels["ray.io/provider-node-id"] = node.node_id
                host_labels["ray.io/node-type"] = node_type.name
                handle = self._cluster.add_node(
                    resources=dict(node_type.resources), labels=host_labels)
                with self._lock:
                    self._nodes[node.node_id] = node
                    self._handles[node.node_id] = handle
                out.append(node)
        return out

    def terminate_node(self, node: ProviderNode) -> None:
        with self._lock:
            self._nodes.pop(node.node_id, None)
            handle = self._handles.pop(node.node_id, None)
        if handle is not None:
            self._cluster.remove_node(handle)

    def non_terminated_nodes(self) -> List[ProviderNode]:
        with self._lock:
            return list(self._nodes.values())

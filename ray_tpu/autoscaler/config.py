"""Autoscaler cluster config (reference: cluster YAML schema
``python/ray/autoscaler/ray-schema.json`` — ``available_node_types`` with
per-type resources, min/max workers; TPU-first: a node type may describe a
whole TPU slice, which scales atomically at slice granularity the way
queued-resources provisioning does)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    min_workers: int = 0
    max_workers: int = 10
    # TPU slices scale as gangs: hosts_per_slice nodes are launched/terminated
    # together and share a generated slice-name label (reference:
    # _private/accelerators/tpu.py slice model + util/tpu.py reservation)
    hosts_per_slice: int = 1
    slice_label_key: str = "ray.io/tpu-slice-name"

    @property
    def is_slice(self) -> bool:
        return self.hosts_per_slice > 1


@dataclass
class ClusterConfig:
    node_types: Dict[str, NodeTypeConfig]
    # seconds a node must be idle before scale-down considers it
    idle_timeout_s: float = 60.0
    # max fraction of current size to add per round (>=1 node always allowed)
    upscaling_speed: float = 1.0
    max_total_nodes: int = 100

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterConfig":
        types = {
            name: NodeTypeConfig(
                name=name,
                resources=dict(t.get("resources", {})),
                labels=dict(t.get("labels", {})),
                min_workers=int(t.get("min_workers", 0)),
                max_workers=int(t.get("max_workers", 10)),
                hosts_per_slice=int(t.get("hosts_per_slice", 1)),
            )
            for name, t in d.get("available_node_types", {}).items()
        }
        return cls(
            node_types=types,
            idle_timeout_s=float(d.get("idle_timeout_s", 60.0)),
            upscaling_speed=float(d.get("upscaling_speed", 1.0)),
            max_total_nodes=int(d.get("max_total_nodes", 100)),
        )

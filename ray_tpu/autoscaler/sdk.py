"""Programmatic autoscaler hints (reference:
python/ray/autoscaler/sdk.py ``request_resources``)."""

from __future__ import annotations

from ray_tpu._private import wire
from typing import Dict, List, Optional


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None):
    """Ask the autoscaler to scale so these shapes could be placed
    immediately (does not run anything). Persisted in the GCS KV and read
    every reconcile round; overwrite with [] to clear."""
    shapes: List[Dict[str, float]] = []
    if num_cpus:
        shapes.append({"CPU": float(num_cpus)})
    if bundles:
        shapes.extend({k: float(v) for k, v in b.items()} for b in bundles)
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    core._run(core._gcs_call("KVPut", {
        "ns": "autoscaler", "key": "request_resources",
        "value": wire.dumps(shapes)}))

"""Autoscaler v2: per-instance lifecycle state machine + reconciler.

Reference: python/ray/autoscaler/v2/instance_manager/ — v2 replaced v1's
launch-and-forget loop with an explicit per-instance state machine
(QUEUED → REQUESTED → ALLOCATED → RAY_RUNNING → RAY_STOPPING →
TERMINATING → TERMINATED, with ALLOCATION_FAILED retries), durable
instance storage, and a reconciler that converges instance states against
both the cloud provider's view and the GCS's live-node view. This build
keeps v1's demand scheduler (resource_demand_scheduler.py) for target
computation and adds the v2 lifecycle underneath it.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("ray_tpu.autoscaler.v2")

# the label raylets carry to map GCS nodes back to provider nodes — import
# the real constant so the join cannot drift from what providers set
from ray_tpu.autoscaler.autoscaler import PROVIDER_ID_LABEL

# lifecycle states (reference: instance_manager/common.py InstanceUtil)
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
RAY_STOPPING = "RAY_STOPPING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"

_TRANSITIONS = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED, TERMINATING},
    ALLOCATED: {RAY_RUNNING, TERMINATING},
    RAY_RUNNING: {RAY_STOPPING, TERMINATING},
    RAY_STOPPING: {TERMINATING},
    TERMINATING: {TERMINATED},
    ALLOCATION_FAILED: {QUEUED, TERMINATED},
    TERMINATED: set(),
}

# states that count toward a node type's live capacity target
ACTIVE_STATES = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING)


class InvalidTransition(RuntimeError):
    pass


@dataclass
class Instance:
    instance_id: str
    node_type: str
    state: str = QUEUED
    provider_node_id: str = ""
    raylet_node_id: str = ""
    slice_name: str = ""
    created_at: float = field(default_factory=time.time)
    state_since: float = field(default_factory=time.time)
    last_seen: float = 0.0  # last time the GCS reported the node alive
    retries: int = 0
    history: List[tuple] = field(default_factory=list)  # (ts, from, to, why)

    def dump(self) -> dict:
        return {k: getattr(self, k) for k in (
            "instance_id", "node_type", "state", "provider_node_id",
            "raylet_node_id", "slice_name", "created_at", "state_since",
            "last_seen", "retries")}

    @classmethod
    def restore(cls, d: dict) -> "Instance":
        inst = cls(instance_id=d["instance_id"], node_type=d["node_type"])
        for k, v in d.items():
            setattr(inst, k, v)
        return inst


class InstanceManager:
    """Owns every instance's lifecycle; persists through a pluggable
    store (dict-like: __setitem__/__delitem__/values) so a restarted
    autoscaler resumes mid-flight instances instead of double-launching."""

    def __init__(self, store: Optional[Any] = None,
                 request_timeout_s: float = 120.0,
                 ray_start_timeout_s: float = 300.0,
                 max_allocation_retries: int = 3,
                 retry_backoff_s: float = 5.0):
        self._store = store if store is not None else {}
        self.request_timeout_s = request_timeout_s
        self.ray_start_timeout_s = ray_start_timeout_s
        self.max_allocation_retries = max_allocation_retries
        self.retry_backoff_s = retry_backoff_s
        self.instances: Dict[str, Instance] = {}
        for d in list(self._store.values()):
            inst = Instance.restore(d)
            self.instances[inst.instance_id] = inst

    # -- state machine -------------------------------------------------

    def transition(self, inst: Instance, to: str, why: str = "") -> None:
        if to not in _TRANSITIONS[inst.state]:
            raise InvalidTransition(
                f"{inst.instance_id[:8]}: {inst.state} -> {to} ({why!r})")
        inst.history.append((time.time(), inst.state, to, why))
        logger.info("instance %s (%s): %s -> %s%s", inst.instance_id[:8],
                    inst.node_type, inst.state, to,
                    f" ({why})" if why else "")
        inst.state = to
        inst.state_since = time.time()
        if to == TERMINATED:
            self.instances.pop(inst.instance_id, None)
            try:
                del self._store[inst.instance_id]
            except KeyError:
                pass
        else:
            self._store[inst.instance_id] = inst.dump()

    def add(self, node_type: str) -> Instance:
        inst = Instance(instance_id=uuid.uuid4().hex, node_type=node_type)
        self.instances[inst.instance_id] = inst
        self._store[inst.instance_id] = inst.dump()
        return inst

    def by_state(self, *states: str) -> List[Instance]:
        return [i for i in self.instances.values() if i.state in states]

    def active_count(self, node_type: str) -> int:
        return sum(1 for i in self.instances.values()
                   if i.node_type == node_type and i.state in ACTIVE_STATES)

    # -- reconciliation ------------------------------------------------

    def set_targets(self, targets: Dict[str, int]) -> None:
        """Converge queued/surplus instances toward per-type targets."""
        for node_type, want in targets.items():
            have = self.active_count(node_type)
            for _ in range(max(0, want - have)):
                self.add(node_type)
        for node_type, want in targets.items():
            surplus = self.active_count(node_type) - want
            if surplus <= 0:
                continue
            # shed from the least-committed end first; every shed here
            # is an ACTIVE instance, so the surplus accounting stays true
            # (failed instances are not active and retire via step())
            shed_plan = ((QUEUED, TERMINATED), (REQUESTED, TERMINATING),
                         (ALLOCATED, TERMINATING), (RAY_RUNNING, RAY_STOPPING))
            for state, to in shed_plan:
                for inst in self.by_state(state):
                    if surplus <= 0:
                        break
                    if inst.node_type == node_type:
                        self.transition(inst, to, "target shrank")
                        surplus -= 1

    def step(self, provider, node_types: Dict[str, Any],
             gcs_nodes: Optional[List[dict]] = None,
             drain: Optional[Callable[[str], None]] = None) -> dict:
        """One reconcile pass against the provider + GCS views."""
        now = time.time()
        provider_nodes = {n.node_id: n for n in provider.non_terminated_nodes()}
        gcs_by_provider: Dict[str, dict] = {}
        for n in gcs_nodes or []:
            pid = n.get("labels", {}).get(PROVIDER_ID_LABEL, "")
            if pid:
                gcs_by_provider[pid] = n

        # QUEUED -> REQUESTED (respecting retry backoff)
        for inst in self.by_state(QUEUED):
            if inst.retries and now - inst.state_since < \
                    self.retry_backoff_s * (2 ** (inst.retries - 1)):
                continue
            t = node_types[inst.node_type]
            try:
                nodes = provider.create_nodes(t, 1)
            except Exception as e:
                self.transition(inst, REQUESTED, "launch call")
                self.transition(inst, ALLOCATION_FAILED, str(e))
                continue
            self.transition(inst, REQUESTED, "launch call")
            if nodes:
                inst.provider_node_id = nodes[0].node_id
                inst.slice_name = getattr(nodes[0], "slice_name", "")
                self.transition(inst, ALLOCATED, "provider returned node")
            # async providers return later; found via provider view below

        # REQUESTED -> ALLOCATED / ALLOCATION_FAILED (timeout). Async
        # providers return no node from create_nodes(): adopt an unclaimed
        # provider node of the right type from the view, so a late
        # provision is tracked instead of leaking while we re-launch.
        claimed = {i.provider_node_id for i in self.instances.values()
                   if i.provider_node_id}
        for inst in self.by_state(REQUESTED):
            if inst.provider_node_id and inst.provider_node_id in provider_nodes:
                self.transition(inst, ALLOCATED, "provider view")
                continue
            if not inst.provider_node_id:
                orphan = next(
                    (n for n in provider_nodes.values()
                     if n.node_id not in claimed
                     and getattr(n, "node_type", "") == inst.node_type), None)
                if orphan is not None:
                    inst.provider_node_id = orphan.node_id
                    inst.slice_name = getattr(orphan, "slice_name", "")
                    claimed.add(orphan.node_id)
                    self.transition(inst, ALLOCATED, "adopted provider node")
                    continue
            if now - inst.state_since > self.request_timeout_s:
                self.transition(inst, ALLOCATION_FAILED, "request timed out")

        # ALLOCATION_FAILED -> QUEUED (retry) or TERMINATED (gave up)
        for inst in self.by_state(ALLOCATION_FAILED):
            if inst.retries + 1 > self.max_allocation_retries:
                self.transition(inst, TERMINATED,
                                f"gave up after {inst.retries} retries")
            else:
                inst.retries += 1
                inst.provider_node_id = ""
                self.transition(inst, QUEUED,
                                f"retry {inst.retries}")

        # ALLOCATED -> RAY_RUNNING when its raylet registers; stuck -> kill
        for inst in self.by_state(ALLOCATED):
            g = gcs_by_provider.get(inst.provider_node_id)
            if g is not None and g.get("alive"):
                inst.raylet_node_id = g.get("node_id", "")
                inst.last_seen = now
                self.transition(inst, RAY_RUNNING, "raylet registered")
            elif now - inst.state_since > self.ray_start_timeout_s:
                self.transition(inst, TERMINATING, "raylet never registered")

        # RAY_RUNNING whose node died under us -> TERMINATING. A node
        # that VANISHED from the GCS view (entry evicted/tombstoned) is
        # dead too — after a grace window covering a missed poll.
        for inst in self.by_state(RAY_RUNNING):
            g = gcs_by_provider.get(inst.provider_node_id)
            if g is not None and g.get("alive", True):
                inst.last_seen = now
            elif g is not None:
                self.transition(inst, TERMINATING, "node died")
            elif gcs_nodes is not None and inst.last_seen                     and now - inst.last_seen > self.request_timeout_s:
                self.transition(inst, TERMINATING, "node vanished from GCS")

        # RAY_STOPPING: drain, then terminate
        for inst in self.by_state(RAY_STOPPING):
            if drain is not None and inst.raylet_node_id:
                try:
                    drain(inst.raylet_node_id)
                except Exception as e:
                    logger.debug("drain of %s failed (instance still "
                                 "terminates): %s", inst.instance_id, e)
            self.transition(inst, TERMINATING, "drained")

        # TERMINATING -> provider terminate -> TERMINATED
        for inst in self.by_state(TERMINATING):
            node = provider_nodes.get(inst.provider_node_id)
            if node is not None:
                try:
                    provider.terminate_node(node)
                except Exception as e:
                    logger.warning("terminate %s failed: %s",
                                   inst.provider_node_id, e)
                    continue
            self.transition(inst, TERMINATED, "provider terminated")

        by_state: Dict[str, int] = {}
        for inst in self.instances.values():
            by_state[inst.state] = by_state.get(inst.state, 0) + 1
        return {"instances": len(self.instances), "by_state": by_state}

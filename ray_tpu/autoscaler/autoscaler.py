"""The autoscaler reconciler.

Reference: v1 ``StandardAutoscaler`` (autoscaler/_private/autoscaler.py)
driven by ``monitor.py`` on the head node, and the v2 reconciler
(``autoscaler/v2/autoscaler.py``) that diffs desired vs. actual instances
against the GCS cluster state. This implementation is reconciler-style:
each step polls the GCS for (nodes, idle info, unplaceable demands),
bin-packs the gap, and drives the NodeProvider. TPU slice types scale as
whole slices (queued-resources semantics)."""

from __future__ import annotations

import logging
from ray_tpu._private import wire
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.rpc import RetryingRpcClient
from ray_tpu.autoscaler.config import ClusterConfig
from ray_tpu.autoscaler.node_provider import NodeProvider, ProviderNode
from ray_tpu.autoscaler.resource_demand_scheduler import (
    get_nodes_to_launch,
    get_nodes_to_terminate,
)

logger = logging.getLogger("ray_tpu.autoscaler")

PROVIDER_ID_LABEL = "ray.io/provider-node-id"
NODE_TYPE_LABEL = "ray.io/node-type"


class Autoscaler:
    def __init__(self, config: ClusterConfig, provider: NodeProvider,
                 gcs_address: str):
        self.config = config
        self.provider = provider
        self.gcs_address = gcs_address
        self._client: Optional[RetryingRpcClient] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_status: dict = {}

    # -- GCS I/O -------------------------------------------------------

    def _gcs(self, method: str, req: dict) -> dict:
        import asyncio

        async def _call():
            client = RetryingRpcClient(self.gcs_address)
            try:
                return wire.loads(
                    await client.call(method, wire.dumps(req), timeout=10.0))
            finally:
                await client.close()

        return asyncio.run(_call())

    # -- one reconcile round -------------------------------------------

    def step(self) -> dict:
        """Poll state, launch/terminate, return a status summary."""
        status = self._gcs("GetClusterStatus", {})
        provider_nodes = {n.node_id: n for n in self.provider.non_terminated_nodes()}

        # join GCS nodes to provider nodes via the provider-id label
        gcs_by_provider_id: Dict[str, dict] = {}
        for n in status["nodes"]:
            pid = n["labels"].get(PROVIDER_ID_LABEL, "")
            if pid:
                gcs_by_provider_id[pid] = n

        existing_by_type: Dict[str, int] = {}
        for node in provider_nodes.values():
            existing_by_type[node.node_type] = existing_by_type.get(node.node_type, 0) + 1
        # slices count once per slice, not per host
        for name, t in self.config.node_types.items():
            if t.is_slice and name in existing_by_type:
                existing_by_type[name] = existing_by_type[name] // t.hosts_per_slice

        demands = [{"shape": d["shape"], "selector": d.get("selector", {})}
                   for d in status.get("demands", [])
                   for _ in range(d.get("count", 1))]
        demands += [dict(s) for s in self._request_resources_hints()]
        node_available = [{"available": n["available"], "labels": n["labels"]}
                          for n in status["nodes"] if n["alive"]]
        strict_spread = status.get("strict_spread", [])

        launch = get_nodes_to_launch(
            self.config, existing_by_type, node_available, demands, strict_spread)
        launched: List[ProviderNode] = []
        for name, count in launch.items():
            t = self.config.node_types[name]
            t = _with_provider_labels(t)
            launched.extend(self.provider.create_nodes(t, count))
            logger.info("autoscaler: launched %d x %s", count, name)

        # scale-down: idle beyond timeout and above min
        node_views = []
        for node in provider_nodes.values():
            g = gcs_by_provider_id.get(node.node_id)
            if g is None or not g["alive"]:
                continue
            node_views.append({
                "node_type": node.node_type,
                "idle_s": g.get("idle_s", 0.0),
                "used": g.get("used", False),
                "slice_name": node.slice_name,
                "_provider_node": node,
                "_gcs_node_id": g["node_id"],
            })
        victims = get_nodes_to_terminate(self.config, node_views)
        for v in victims:
            logger.info("autoscaler: terminating idle node %s (%s)",
                        v["_gcs_node_id"][:8], v["node_type"])
            try:
                self._gcs("DrainNode", {"node_id": _node_id_from_hex(v["_gcs_node_id"])})
            except Exception as e:
                logger.debug("DrainNode %s failed (retried next tick): %s",
                             v["_gcs_node_id"][:8], e)
            self.provider.terminate_node(v["_provider_node"])

        self.last_status = {
            "nodes": len(provider_nodes) + len(launched) - len(victims),
            "launched": {k: v for k, v in launch.items()},
            "terminated": len(victims),
            "pending_demands": len(demands),
        }
        return self.last_status

    def _request_resources_hints(self) -> List[Dict[str, float]]:
        """Explicit demand set via sdk.request_resources (kv-backed)."""
        try:
            reply = self._gcs("KVGet", {"ns": "autoscaler", "key": "request_resources"})
            blob = reply.get("value")
            return wire.loads(blob) if blob else []
        except Exception:
            return []

    # -- background loop ------------------------------------------------

    def start(self, interval_s: float = 1.0):
        def loop():
            while not self._stop.is_set():
                try:
                    self.step()
                except Exception:
                    logger.exception("autoscaler step failed")
                self._stop.wait(interval_s)

        self._thread = threading.Thread(target=loop, daemon=True, name="autoscaler")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5.0)


def _with_provider_labels(t):
    import copy
    import uuid

    t = copy.deepcopy(t)
    t.labels[NODE_TYPE_LABEL] = t.name
    return t


def _node_id_from_hex(hex_str: str):
    from ray_tpu._private.ids import NodeID

    return NodeID.from_hex(hex_str)


def main():
    import argparse
    import json

    from ray_tpu._private.logs import setup_process_logging

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--config", required=True, help="path to cluster config JSON")
    parser.add_argument("--interval", type=float, default=5.0)
    parser.add_argument("--log-dir", default="")
    args = parser.parse_args()
    setup_process_logging("autoscaler", args.log_dir)
    with open(args.config) as f:
        config = ClusterConfig.from_dict(json.load(f))
    raise SystemExit(
        "standalone monitor requires a cloud NodeProvider plugin; "
        "see ray_tpu.autoscaler.node_provider.NodeProvider")


if __name__ == "__main__":
    main()

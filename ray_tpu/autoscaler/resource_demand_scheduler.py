"""Bin-packing of pending resource demands onto node types.

Reference: ``python/ray/autoscaler/_private/resource_demand_scheduler.py`` —
given (a) resource shapes the cluster cannot currently place, (b) existing
nodes, and (c) the node-type catalog, decide how many nodes of which types
to add, respecting min/max workers. Strict-spread placement-group shapes
count one node per bundle. TPU slice types are all-or-nothing gangs."""

from __future__ import annotations

import copy
from typing import Dict, List, Tuple

from ray_tpu._private.common import label_match
from ray_tpu.autoscaler.config import ClusterConfig, NodeTypeConfig


def _fits(avail: Dict[str, float], shape: Dict[str, float],
          labels: Dict[str, str] = None, selector: Dict[str, str] = None) -> bool:
    if selector and not label_match(labels or {}, selector):
        return False
    return all(avail.get(k, 0.0) >= v for k, v in shape.items() if v > 0)


def _sub(avail: Dict[str, float], shape: Dict[str, float]) -> None:
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v


def _norm_demand(d) -> Tuple[Dict[str, float], Dict[str, str]]:
    """Accepts a bare shape dict or {'shape':..., 'selector':...}."""
    if isinstance(d, dict) and "shape" in d:
        return dict(d["shape"]), dict(d.get("selector") or {})
    return dict(d), {}


def get_nodes_to_launch(
    config: ClusterConfig,
    existing_by_type: Dict[str, int],
    node_available: List,
    demands: List,
    strict_spread_shapes: List[List[Dict[str, float]]] = (),
) -> Dict[str, int]:
    """Returns {node_type: count} to launch.

    ``node_available`` holds per-node free-resource views of the live
    cluster — either bare dicts or {'available':..., 'labels':...};
    demands (bare shapes or {'shape','selector'}) that fit on it are
    dropped (they'll schedule without scaling). The rest are
    first-fit-decreasing packed onto virtual copies of node types, with
    label selectors honored against node/type labels."""
    to_launch: Dict[str, int] = {}

    # honor min_workers before anything else (exempt from the upscaling-speed
    # budget: a cluster below its floor always scales straight to it)
    min_launch: Dict[str, int] = {}
    for name, t in config.node_types.items():
        have = existing_by_type.get(name, 0)
        if have < t.min_workers:
            min_launch[name] = t.min_workers - have
            to_launch[name] = min_launch[name]

    free: List[Tuple[Dict[str, float], Dict[str, str]]] = []
    for a in node_available:
        if isinstance(a, dict) and "available" in a:
            free.append((dict(a["available"]), dict(a.get("labels") or {})))
        else:
            free.append((dict(a), {}))
    # virtual nodes created this round (free capacity still packable)
    virtual: List[Tuple[str, Dict[str, float], Dict[str, str]]] = []

    def _add_virtual(t: NodeTypeConfig):
        for _ in range(t.hosts_per_slice):
            virtual.append((t.name, dict(t.resources), dict(t.labels)))

    for name, n in to_launch.items():
        for _ in range(n):
            _add_virtual(config.node_types[name])

    norm = [_norm_demand(d) for d in demands]
    unmet: List[Tuple[Dict[str, float], Dict[str, str]]] = []
    order = sorted(norm, key=lambda d: -sum(d[0].values()))
    for shape, selector in order:
        placed = False
        for avail, labels in free:
            if _fits(avail, shape, labels, selector):
                _sub(avail, shape)
                placed = True
                break
        if not placed:
            for _, avail, labels in virtual:
                if _fits(avail, shape, labels, selector):
                    _sub(avail, shape)
                    placed = True
                    break
        if not placed:
            unmet.append((shape, selector))

    # pick node types for unmet shapes: smallest type that fits each shape
    # (first-fit-decreasing over a cost = sum of resources)
    types_by_cost = sorted(
        config.node_types.values(), key=lambda t: sum(t.resources.values()))
    for shape, selector in unmet:
        chosen = None
        for t in types_by_cost:
            if _fits(dict(t.resources), shape, t.labels, selector):
                chosen = t
                break
        if chosen is None:
            continue  # infeasible on any type; surface via status instead
        have = existing_by_type.get(chosen.name, 0) + to_launch.get(chosen.name, 0)
        if have >= chosen.max_workers:
            continue
        to_launch[chosen.name] = to_launch.get(chosen.name, 0) + 1
        _add_virtual(chosen)
        # retro-fit: this new node may absorb later shapes via `virtual`

    # strict-spread groups: each bundle needs a distinct node
    for bundles in strict_spread_shapes:
        nodes_needed = 0
        scratch = ([dict(a) for a, _ in free]
                   + [dict(a) for _, a, _ in virtual])
        used = [False] * len(scratch)
        for b in bundles:
            placed = False
            for i, avail in enumerate(scratch):
                if not used[i] and _fits(avail, b):
                    used[i] = True
                    _sub(avail, b)
                    placed = True
                    break
            if not placed:
                nodes_needed += 1
        if nodes_needed:
            # smallest type that fits the largest bundle
            biggest = max(bundles, key=lambda s: sum(s.values()))
            for t in types_by_cost:
                if _fits(dict(t.resources), biggest):
                    have = (existing_by_type.get(t.name, 0)
                            + to_launch.get(t.name, 0))
                    add = min(nodes_needed, max(0, t.max_workers - have))
                    if add:
                        to_launch[t.name] = to_launch.get(t.name, 0) + add
                    break

    # cap demand-driven launches by cluster size and upscaling speed
    # (min_workers launches bypass the speed budget, not the size cap)
    total_existing = sum(existing_by_type.values())
    budget = max(1, int(config.upscaling_speed * max(total_existing, 1)))
    capped: Dict[str, int] = {}
    room = max(0, config.max_total_nodes - total_existing)
    for name, n in to_launch.items():
        floor = min(min_launch.get(name, 0), n, room)
        extra = min(n - floor, budget, max(0, room - floor))
        take = floor + extra
        if take > 0:
            capped[name] = take
            budget -= extra
            room -= take * config.node_types[name].hosts_per_slice
    return capped


def get_nodes_to_terminate(
    config: ClusterConfig,
    nodes: List[dict],
) -> List[dict]:
    """Scale-down: idle (no used resources) longer than idle_timeout_s and
    above min_workers. ``nodes`` entries: {"node_type", "idle_s", "used"}.
    Slice gangs terminate only when every host of the slice is idle."""
    by_type: Dict[str, List[dict]] = {}
    for n in nodes:
        by_type.setdefault(n["node_type"], []).append(n)
    victims: List[dict] = []
    for name, members in by_type.items():
        t = config.node_types.get(name)
        if t is None:
            continue
        idle = [n for n in members
                if n["idle_s"] >= config.idle_timeout_s and not n["used"]]
        if t.is_slice:
            # group by slice; a slice is terminable only if all hosts idle
            slices: Dict[str, List[dict]] = {}
            for n in members:
                slices.setdefault(n.get("slice_name", ""), []).append(n)
            removable = []
            for sname, hosts in slices.items():
                if all(h in idle for h in hosts):
                    removable.append(hosts)
            keep = t.min_workers
            for hosts in removable[: max(0, len(removable) - keep)]:
                victims.extend(hosts)
        else:
            excess = len(members) - t.min_workers
            idle.sort(key=lambda n: -n["idle_s"])
            victims.extend(idle[: max(0, min(len(idle), excess))])
    return victims

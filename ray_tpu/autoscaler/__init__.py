from ray_tpu.autoscaler.config import ClusterConfig, NodeTypeConfig
from ray_tpu.autoscaler.autoscaler import Autoscaler
from ray_tpu.autoscaler.node_provider import (
    FakeMultiNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.sdk import request_resources

__all__ = [
    "Autoscaler",
    "ClusterConfig",
    "NodeTypeConfig",
    "NodeProvider",
    "FakeMultiNodeProvider",
    "request_resources",
]

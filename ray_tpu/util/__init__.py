"""ray_tpu.util: actor pool, queue, placement groups, scheduling strategies."""

from __future__ import annotations

from typing import Any, Callable, List

import ray_tpu
from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    remove_placement_group,
)


class ActorPool:
    """Work distribution over a fixed set of actors (reference:
    python/ray/util/actor_pool.py)."""

    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending = []  # (fn, value) waiting for an idle actor

    def submit(self, fn: Callable, value: Any):
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
        else:
            self._pending.append((fn, value))

    def _drain_pending(self):
        while self._pending and self._idle:
            fn, value = self._pending.pop(0)
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor

    def get_next(self, timeout: float = 300.0):
        if not self._future_to_actor:
            raise StopIteration("no pending work")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("ActorPool.get_next timed out")
        ref = ready[0]
        actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        self._drain_pending()
        return ray_tpu.get(ref)

    def map(self, fn: Callable, values: List[Any]):
        for v in values:
            self.submit(fn, v)
        out = []
        for _ in values:
            out.append(self.get_next())
        return out

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending)


@ray_tpu.remote(num_cpus=0.1)
class _QueueActor:
    def __init__(self, maxsize: int = 0):
        import asyncio

        self._q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout=None):
        import asyncio

        await asyncio.wait_for(self._q.put(item), timeout)
        return True

    async def get(self, timeout=None):
        import asyncio

        return await asyncio.wait_for(self._q.get(), timeout)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()


class Queue:
    """Distributed FIFO queue backed by an actor (reference:
    python/ray/util/queue.py)."""

    def __init__(self, maxsize: int = 0, name: str = ""):
        opts = {"max_concurrency": 16, "num_cpus": 0.1}
        if name:
            opts.update(name=name, get_if_exists=True, lifetime="detached")
        self._actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item, timeout: float = None):
        ray_tpu.get(self._actor.put.remote(item, timeout), timeout=timeout or 300)

    def get(self, timeout: float = None):
        return ray_tpu.get(self._actor.get.remote(timeout),
                           timeout=(timeout or 300) + 10)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote(), timeout=60)

    def empty(self) -> bool:
        return ray_tpu.get(self._actor.empty.remote(), timeout=60)

    def shutdown(self):
        ray_tpu.kill(self._actor)

"""Placement groups: gang resource reservation across nodes.

Reference: python/ray/util/placement_group.py + GCS 2PC scheduling
(``gcs_placement_group_scheduler.h:115-118``). Strategies: PACK, SPREAD,
STRICT_PACK, STRICT_SPREAD; bundles may carry label selectors — the hook TPU
slice gang scheduling builds on (``ray_tpu/util/tpu.py``).
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional

from ray_tpu._private.common import Bundle, PlacementGroupSpec
from ray_tpu._private.ids import PlacementGroupID
from ray_tpu.exceptions import PlacementGroupError


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self, timeout: float = 300.0) -> bool:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod.global_worker()
        reply = core._run(core._gcs_call("WaitPlacementGroupReady", {
            "pg_id": self.id.binary(), "timeout": timeout}, timeout=timeout + 10))
        if reply["status"] == "ready":
            return True
        if reply["status"] == "timeout":
            return False
        raise PlacementGroupError(f"placement group state: {reply['status']}")

    def wait(self, timeout_seconds: float = 300.0) -> bool:
        return self.ready(timeout_seconds)

    def bundle_nodes(self) -> List[str]:
        from ray_tpu._private import worker as worker_mod

        core = worker_mod.global_worker()
        info = core._run(core._gcs_call("GetPlacementGroup",
                                        {"pg_id": self.id.binary()}))["info"]
        return info["bundle_nodes"] if info else []

    def __reduce__(self):
        return (_rebuild_pg, (self.id.binary(), self.bundle_specs))


def _rebuild_pg(id_bytes, bundles):
    return PlacementGroup(PlacementGroupID(id_bytes), bundles)


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: str = "ref_counted",
    bundle_label_selector: Optional[List[Dict[str, str]]] = None,
) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"unknown strategy {strategy!r}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    selectors = bundle_label_selector or [{}] * len(bundles)
    spec = PlacementGroupSpec(
        pg_id=PlacementGroupID.from_random(),
        bundles=[Bundle(resources=dict(b), label_selector=dict(s))
                 for b, s in zip(bundles, selectors)],
        strategy=strategy,
        name=name,
        lifetime=lifetime,
        creator_job=core.job_id,
    )
    core._run(core._gcs_call("CreatePlacementGroup", {"spec": spec}))
    return PlacementGroup(spec.pg_id, [dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup):
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    core._run(core._gcs_call("RemovePlacementGroup", {"pg_id": pg.id.binary()}))


def get_placement_group_state(pg: PlacementGroup) -> Optional[dict]:
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    return core._run(core._gcs_call("GetPlacementGroup", {"pg_id": pg.id.binary()}))["info"]

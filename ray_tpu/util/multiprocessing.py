"""``multiprocessing.Pool`` drop-in on cluster tasks.

Reference: python/ray/util/multiprocessing — the Pool shim that lets
stdlib-Pool code scale across a cluster unchanged. Work items run as
framework tasks; ``processes`` caps in-flight parallelism.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


@ray_tpu.remote
def _pool_apply(fn_blob: bytes, args, kwargs):
    from ray_tpu._private.serialization import loads_trusted

    fn = loads_trusted(fn_blob)
    return fn(*args, **(kwargs or {}))


class AsyncResult:
    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout: Optional[float] = None):
        return ray_tpu.get(self._ref, timeout=timeout)

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait([self._ref], timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait([self._ref], timeout=0)
        return bool(ready)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    """API-compatible subset of multiprocessing.Pool over cluster tasks."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None, initargs: tuple = (),
                 ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        self._processes = processes
        self._remote_args = ray_remote_args or {}
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    def _submit(self, fn, args, kwargs=None):
        import cloudpickle

        if self._initializer is not None:
            init, initargs = self._initializer, self._initargs

            def wrapped(*a, _fn=fn, **kw):
                init(*initargs)
                return _fn(*a, **kw)

            blob = cloudpickle.dumps(wrapped)
        else:
            blob = cloudpickle.dumps(fn)
        task = _pool_apply
        if self._remote_args:
            task = task.options(**self._remote_args)
        return task.remote(blob, tuple(args), kwargs)

    def _bounded_map(self, fn, chunks: List[tuple]) -> List[Any]:
        out_refs: List[Any] = []
        in_flight: List[Any] = []
        for args in chunks:
            if len(in_flight) >= self._processes:
                ready, in_flight = ray_tpu.wait(
                    in_flight, num_returns=1, timeout=None)
                in_flight = list(in_flight)
            ref = self._submit(fn, args)
            out_refs.append(ref)
            in_flight.append(ref)
        return ray_tpu.get(out_refs)

    # -- Pool API ------------------------------------------------------

    def apply(self, fn, args: tuple = (), kwds: Optional[dict] = None):
        return ray_tpu.get(self._submit(fn, args, kwds))

    def apply_async(self, fn, args: tuple = (), kwds: Optional[dict] = None
                    ) -> AsyncResult:
        return AsyncResult(self._submit(fn, args, kwds))

    def map(self, fn, iterable: Iterable, chunksize: Optional[int] = None):
        return self._bounded_map(fn, [(x,) for x in iterable])

    def map_async(self, fn, iterable: Iterable) -> List[AsyncResult]:
        return [self.apply_async(fn, (x,)) for x in iterable]

    def starmap(self, fn, iterable: Iterable[tuple]):
        return self._bounded_map(fn, [tuple(x) for x in iterable])

    def imap(self, fn, iterable: Iterable, chunksize: Optional[int] = None):
        refs = [self._submit(fn, (x,)) for x in iterable]
        for ref in refs:
            yield ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable: Iterable,
                       chunksize: Optional[int] = None):
        pending = [self._submit(fn, (x,)) for x in iterable]
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            pending = list(pending)
            yield ray_tpu.get(ready[0])

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

"""State API: inspect cluster state (reference: python/ray/util/state/api.py
list_* :790-1304, backed by the GCS instead of a dashboard process)."""

from __future__ import annotations

from ray_tpu._private import wire
from typing import Any, Dict, List, Optional


def _state() -> Dict[str, Any]:
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker().get_state()


def list_nodes() -> List[Dict[str, Any]]:
    return _state()["nodes"]


def list_actors(state_filter: Optional[str] = None) -> List[Dict[str, Any]]:
    actors = _state()["actors"]
    if state_filter:
        actors = [a for a in actors if a["state"] == state_filter]
    return actors


def list_jobs() -> List[Dict[str, Any]]:
    return _state()["jobs"]


def list_placement_groups() -> List[Dict[str, Any]]:
    return _state()["pgs"]


def _core():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker()


def list_tasks(job_id: Optional[str] = None, name: Optional[str] = None,
               state_filter: Optional[str] = None,
               limit: int = 200) -> List[Dict[str, Any]]:
    """Task lifecycle records from the GCS task manager (reference:
    ``ray list tasks`` backed by GcsTaskManager). Each record carries the
    full timestamped state-transition history (SUBMITTED →
    LEASE_REQUESTED → SCHEDULED → RUNNING → FINISHED/FAILED, plus RETRYING
    entries with attempt count and error summary)."""
    core = _core()
    if getattr(core, "mode", "") == "local":
        return []  # local mode executes inline; there is no lifecycle
    return core._run(core._gcs_call("ListTasks", {
        "job_id": job_id, "name": name, "state": state_filter,
        "limit": limit}), 30.0)["tasks"]


def get_task(task_id: str) -> Optional[Dict[str, Any]]:
    """One task's lifecycle record by hex task id (``ray get tasks``)."""
    core = _core()
    if getattr(core, "mode", "") == "local":
        return None
    return core._run(core._gcs_call("GetTask", {"task_id": task_id}),
                     30.0)["task"]


def summarize_tasks(job_id: Optional[str] = None) -> Dict[str, Any]:
    """Per-function counts by lifecycle state — the ``ray summary tasks``
    analog. Includes per-function object-size accounting
    (``per_function_bytes``: summed serialized arg bytes from SUBMITTED
    events and returned-object bytes from terminal events) and the
    GCS-side drop counters so ring truncation is visible."""
    core = _core()
    if getattr(core, "mode", "") == "local":
        return {"per_function": {}, "per_function_bytes": {}, "total": 0,
                "dropped": {}}
    return core._run(core._gcs_call("SummarizeTasks", {"job_id": job_id}),
                     30.0)


def metrics_history(name: Optional[str] = None,
                    window_s: Optional[float] = None,
                    tier: str = "auto") -> Any:
    """Metric time-series from the GCS history ring (reference surface:
    the dashboard's ``/api/metrics/history``). Without ``name``, returns
    the list of recorded metric names. With it, returns ``{"name",
    "kind", "tier", "interval_s", "points"}`` — ``tier="raw"`` is the
    fine ring (default 5 s cadence), ``"rollup"`` the coarse one (default
    60 s: avg/min/max for gauges, cumulative + rate for counters and
    histograms), ``"auto"`` picks raw while the window fits in it."""
    core = _core()
    if not name:
        return core._run(core._gcs_call("GetMetricsHistory", {}),
                         30.0)["names"]
    return core._run(core._gcs_call("GetMetricsHistory", {
        "name": name, "window_s": window_s, "tier": tier}), 30.0)["history"]


def cluster_health(scan: bool = False) -> Dict[str, Any]:
    """Latest cluster-health report from the GCS monitor: stuck tasks
    (RUNNING far past the per-function p99), straggler raylets
    (lease-queue/loop-lag outliers, lagging heartbeats), and
    provisioning-pool pathology (dead zygote, starved warm pool).
    ``scan=True`` forces a scan now instead of returning the last
    periodic one (``health_scan_interval_s``)."""
    core = _core()
    return core._run(core._gcs_call("GetClusterHealth", {"scan": scan}),
                     60.0)["health"]


def goodput(job: Optional[str] = None) -> Dict[str, Any]:
    """Per-job goodput ledgers from the GCS (``/api/goodput`` surface):
    cumulative wall-clock attribution buckets (``step_compute``,
    ``collective_wait``, ``input_stall``, ``ckpt_pause``, ``compile``,
    ``reform_downtime``, ``bubble``, ``overhead``, ``idle``) summed over
    the job's processes, plus counters (steps, compiles, RE-compiles,
    ckpt saves, reforms) and the derived ``goodput_fraction``
    (step_compute share of wall). ``job`` filters to one run name."""
    core = _core()
    req: Dict[str, Any] = {}
    if job:
        req["job"] = job
    return core._run(core._gcs_call("GetGoodput", req), 30.0)["jobs"]


def get_timeline(job_id: Optional[str] = None,
                 start_ts: Optional[float] = None,
                 end_ts: Optional[float] = None,
                 spans: bool = True, limit: int = 5000) -> Dict[str, Any]:
    """Perfetto-loadable chrome-trace JSON of the task flow graph from the
    GCS task-event ring (+ built-in spans) — the ``/api/timeline``
    surface, callable from a driver. Dump it with ``json.dump`` and open
    in ui.perfetto.dev."""
    core = _core()
    req: Dict[str, Any] = {"job_id": job_id, "spans": spans, "limit": limit}
    if start_ts is not None:
        req["start_ts"] = start_ts
    if end_ts is not None:
        req["end_ts"] = end_ts
    return core._run(core._gcs_call("GetTimeline", req), 60.0)


def get_node_stats(node_address: str, agent: bool = False) -> Dict[str, Any]:
    """Raylet-side stats; agent=True adds the per-node agent sample (node
    cpu/mem/load + per-worker cpu/rss, reference: dashboard
    modules/reporter)."""
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    client = core._raylet_client(node_address)

    async def _call():
        return wire.loads(await client.call(
            "GetNodeStats", wire.dumps({"agent": agent}), timeout=30.0))

    return core._run(_call())


def profile_worker(node_address: str, pid: int, kind: str = "stacks",
                   **args) -> Dict[str, Any]:
    """Profile one worker process on a node (reference: `ray stack` /
    dashboard py-spy + memray integration). kind="stacks" samples folded
    call stacks; kind="memory" drives the tracemalloc profiler with
    args={"action": "start"|"snapshot"|"stop", ...}."""
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    client = core._raylet_client(node_address)
    timeout = float(args.pop("timeout", 60.0))

    async def _call():
        return wire.loads(await client.call("ProfileWorker", wire.dumps({
            "pid": pid, "kind": kind, "args": args, "timeout": timeout,
        }), timeout=timeout + 10.0))

    return core._run(_call(), timeout + 15.0)


def list_dataset_stats() -> List[Dict[str, Any]]:
    """Per-op runtime metrics of recent Dataset executions (reference:
    data stats surfaced in the dashboard; populated by
    Dataset._publish_stats via GCS KV ns="data_stats")."""
    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    keys = core._run(core._gcs_call(
        "KVKeys", {"ns": "data_stats", "prefix": ""}))["keys"]
    out = []
    for k in keys:
        blob = core._run(core._gcs_call(
            "KVGet", {"ns": "data_stats", "key": k}))["value"]
        if blob is not None:
            entry = wire.loads(blob)
            entry["dataset"] = k
            out.append(entry)
    out.sort(key=lambda e: e.get("ts", 0))
    return out


def _kv_namespace_dump(ns: str) -> Dict[str, Any]:
    """All wire-decoded values of one GCS KV namespace, keyed by KV key —
    the shared read shape of every stats mirror (weights, ckpt, workers,
    ...). One batched KVMultiGet, not a round trip per key."""
    core = _core()
    keys = core._run(core._gcs_call(
        "KVKeys", {"ns": ns, "prefix": ""}), 30.0)["keys"]
    values = core._run(core._gcs_call(
        "KVMultiGet", {"ns": ns, "keys": keys}), 30.0)["values"]
    return {k: wire.loads(blob) for k, blob in values.items()
            if blob is not None}


def list_weight_stores() -> Dict[str, Any]:
    """Weight-plane transfer stats per store (reference surface: the
    dashboard's /api/weights): per-version bytes published/pulled, chunk
    counts, commit timestamps — mirrored to GCS KV ns="weights" by
    WeightStoreActor (ray_tpu/weights/store.py) on every commit/pull."""
    return _kv_namespace_dump("weights")


def list_checkpoints() -> Dict[str, Any]:
    """Checkpoint-plane stores registered with the GCS (reference surface:
    the dashboard's /api/checkpoints): per-store latest/pinned checkpoint
    ids, per-checkpoint step/bytes/dedup stats, retention drop counters
    and — for tiered stores (ray_tpu/ckpt/tier) — per-checkpoint
    residency (local/mirroring/remote/evicted), the remote backend
    descriptor and mirror IO counters. Mirrored to GCS KV ns="ckpt" by
    CheckpointStore/TieredStore on every commit/pin/retention/mirror."""
    return _kv_namespace_dump("ckpt")


def ckpt_sweeps() -> Dict[str, Any]:
    """Latest per-store retention-sweep reports from the GCS-side
    checkpoint sweeper (ns="ckpt_sweep"): dropped manifest/chunk/byte
    counts per tier, the applied policy, and errors. Populated every
    ``ckpt_sweep_interval_s`` for stores that registered a sweep policy."""
    return _kv_namespace_dump("ckpt_sweep")


def serve_state() -> Dict[str, Any]:
    """Serve autoscale plane per deployment (reference surface: the
    dashboard's /api/serve): replica target vs live count, windowed rate
    rollup (arrival rate, queue p99, execute mean), registered SLO
    targets and recent scale transitions — mirrored to GCS KV ns="serve"
    by the serve controller every autoscale tick
    (ray_tpu/serve/api.py _publish_autoscale)."""
    return _kv_namespace_dump("serve")


def list_worker_pools() -> Dict[str, Any]:
    """Per-raylet worker-pool / provisioning-plane stats (reference
    surface: the dashboard's /api/workers): zygote liveness, warm-pool
    size, adoption hit/miss and fork/cold-spawn counters — mirrored to
    GCS KV ns="workers" by each raylet's metrics loop."""
    return _kv_namespace_dump("workers")


def summarize_cluster() -> Dict[str, Any]:
    state = _state()
    actors_by_state: Dict[str, int] = {}
    for a in state["actors"]:
        actors_by_state[a["state"]] = actors_by_state.get(a["state"], 0) + 1
    return {
        "num_nodes": sum(1 for n in state["nodes"] if n["alive"]),
        "num_actors": len(state["actors"]),
        "actors_by_state": actors_by_state,
        "num_jobs": len(state["jobs"]),
        "num_placement_groups": len(state["pgs"]),
        "uptime_s": state.get("uptime_s", 0.0),
    }

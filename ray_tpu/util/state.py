"""State API: inspect cluster state (reference: python/ray/util/state/api.py
list_* :790-1304, backed by the GCS instead of a dashboard process)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def _state() -> Dict[str, Any]:
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker().get_state()


def list_nodes() -> List[Dict[str, Any]]:
    return _state()["nodes"]


def list_actors(state_filter: Optional[str] = None) -> List[Dict[str, Any]]:
    actors = _state()["actors"]
    if state_filter:
        actors = [a for a in actors if a["state"] == state_filter]
    return actors


def list_jobs() -> List[Dict[str, Any]]:
    return _state()["jobs"]


def list_placement_groups() -> List[Dict[str, Any]]:
    return _state()["pgs"]


def get_node_stats(node_address: str) -> Dict[str, Any]:
    import pickle

    from ray_tpu._private import worker as worker_mod

    core = worker_mod.global_worker()
    client = core._raylet_client(node_address)

    async def _call():
        return pickle.loads(await client.call("GetNodeStats", b""))

    return core._run(_call())


def summarize_cluster() -> Dict[str, Any]:
    state = _state()
    actors_by_state: Dict[str, int] = {}
    for a in state["actors"]:
        actors_by_state[a["state"]] = actors_by_state.get(a["state"], 0) + 1
    return {
        "num_nodes": sum(1 for n in state["nodes"] if n["alive"]),
        "num_actors": len(state["actors"]),
        "actors_by_state": actors_by_state,
        "num_jobs": len(state["jobs"]),
        "num_placement_groups": len(state["pgs"]),
        "uptime_s": state.get("uptime_s", 0.0),
    }

"""Client-mode proxy server (the ``ray-tpu://`` endpoint).

Reference: python/ray/util/client (ARCHITECTURE.md) — a gRPC proxy inside
the cluster executes the remote-API operations on behalf of thin external
clients. Here the proxy embeds a driver CoreWorker; each client
connection gets its own object/actor namespace maps, torn down on
disconnect (like the reference's per-client server data servicer).

Start in-cluster with ``start_client_server(port)`` (or
``ray-tpu start --client-server-port N``); connect from anywhere with
``ray_tpu.init(address="ray-tpu://host:port")``.
"""

from __future__ import annotations

import asyncio
import pickle
from typing import Any, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu._private.async_util import spawn
from ray_tpu._private.rpc import RpcServer
from ray_tpu._private.serialization import loads_trusted


class _ClientSession:
    """Per-session state, keyed by a CLIENT-GENERATED id so a transient
    reconnect resumes the same refs/handles (reference: client id channel
    metadata in util/client)."""

    def __init__(self):
        self.refs: Dict[bytes, Any] = {}        # client ref id -> ObjectRef
        self.actors: Dict[bytes, Any] = {}      # actor id -> ActorHandle
        self.owned_actors: Dict[bytes, Any] = {}  # created, non-detached
        self.functions: Dict[str, Any] = {}     # fn hash -> RemoteFunction
        self.classes: Dict[str, Any] = {}       # cls hash -> ActorClass
        self.conn_ids: set = set()


# grace before a disconnected session's resources are reaped (a reconnect
# within the window resumes it)
_REAP_GRACE_S = 60.0


class ClientProxyServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 10001):
        from concurrent.futures import ThreadPoolExecutor

        self.server = RpcServer(self._handle, host, port)
        self.server.on_disconnect = self._on_disconnect
        self.sessions: Dict[str, _ClientSession] = {}
        self._conn_session: Dict[int, str] = {}
        # long-blocking gets/waits each park a thread: give them their own
        # wide pool so they can't starve other clients' traffic
        self._pool = ThreadPoolExecutor(max_workers=256,
                                        thread_name_prefix="client-proxy")

    async def start(self) -> str:
        return await self.server.start()

    async def stop(self):
        await self.server.stop()

    def _session(self, conn, req) -> _ClientSession:
        session_id = req.get("session") or f"conn_{conn.conn_id}"
        sess = self.sessions.get(session_id)
        if sess is None:
            sess = self.sessions[session_id] = _ClientSession()
        sess.conn_ids.add(conn.conn_id)
        self._conn_session[conn.conn_id] = session_id
        return sess

    async def _on_disconnect(self, conn):
        session_id = self._conn_session.pop(conn.conn_id, None)
        if session_id is None:
            return
        sess = self.sessions.get(session_id)
        if sess is None:
            return
        sess.conn_ids.discard(conn.conn_id)
        if not sess.conn_ids:
            spawn(self._reap_after_grace(session_id), what="client-session reap")

    async def _reap_after_grace(self, session_id: str):
        await asyncio.sleep(_REAP_GRACE_S)
        sess = self.sessions.get(session_id)
        if sess is None or sess.conn_ids:
            return  # reconnected within the grace window
        self.sessions.pop(session_id, None)
        # reap this client's refs + the actors it CREATED (detached actors
        # and shared actors merely looked up via GetActor survive)
        try:
            from ray_tpu._private.worker import global_worker

            global_worker().free_objects(list(sess.refs.values()))
        except Exception:
            pass
        for handle in sess.owned_actors.values():
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

    async def _handle(self, method: str, payload: bytes, conn) -> bytes:
        # trusted ingress: payloads execute code on load, so this port must
        # stay inside the cluster trust boundary (no auth of its own); every
        # unpickle goes through the audited serialization chokepoint (SER001)
        req = loads_trusted(payload) if payload else {}
        sess = self._session(conn, req)
        loop = asyncio.get_event_loop()

        def blocking(fn, *args, **kw):
            # every cluster op blocks on CoreWorker round-trips: keep them
            # off this event loop so one slow client can't stall the rest
            return loop.run_in_executor(self._pool, lambda: fn(*args, **kw))

        if method == "Put":
            ref = await blocking(ray_tpu.put, loads_trusted(req["blob"]))
            sess.refs[ref.binary()] = ref
            return pickle.dumps({"ref": ref.binary()})

        if method == "Get":
            refs = [sess.refs[r] for r in req["refs"]]
            try:
                values = await blocking(
                    ray_tpu.get, refs, timeout=req.get("timeout"))
                return pickle.dumps({"status": "ok",
                                     "blob": cloudpickle.dumps(values)})
            except Exception as e:
                return pickle.dumps({"status": "error",
                                     "error": cloudpickle.dumps(e)})

        if method == "Wait":
            refs = [sess.refs[r] for r in req["refs"]]
            ready, pending = await blocking(
                ray_tpu.wait, refs, num_returns=req["num_returns"],
                timeout=req.get("timeout"))
            return pickle.dumps({"ready": [r.binary() for r in ready],
                                 "pending": [r.binary() for r in pending]})

        if method == "SubmitTask":
            fn = sess.functions.get(req["fn_hash"])
            if fn is None:
                fn = ray_tpu.remote(loads_trusted(req["fn_blob"]))
                sess.functions[req["fn_hash"]] = fn
            args, kwargs = self._rebuild_args(sess, req["args_blob"])
            opts = req.get("options") or {}
            target = fn.options(**opts) if opts else fn
            out = await blocking(target.remote, *args, **kwargs)
            out_list = out if isinstance(out, list) else [out]
            for r in out_list:
                sess.refs[r.binary()] = r
            return pickle.dumps({"refs": [r.binary() for r in out_list]})

        if method == "CreateActor":
            cls = sess.classes.get(req["cls_hash"])
            if cls is None:
                cls = ray_tpu.remote(loads_trusted(req["cls_blob"]))
                sess.classes[req["cls_hash"]] = cls
            args, kwargs = self._rebuild_args(sess, req["args_blob"])
            opts = req.get("options") or {}
            target = cls.options(**opts) if opts else cls
            handle = await blocking(target.remote, *args, **kwargs)
            sess.actors[handle.actor_id.binary()] = handle
            if opts.get("lifetime") != "detached":
                sess.owned_actors[handle.actor_id.binary()] = handle
            return pickle.dumps({
                "actor_id": handle.actor_id.binary(),
                "methods": handle._method_names,
                "class_name": handle._class_name,
            })

        if method == "SubmitActorTask":
            handle = sess.actors[req["actor_id"]]
            args, kwargs = self._rebuild_args(sess, req["args_blob"])
            m = getattr(handle, req["method"])
            if req.get("options"):
                m = m.options(**req["options"])
            out = await blocking(m.remote, *args, **kwargs)
            out_list = out if isinstance(out, list) else [out]
            for r in out_list:
                sess.refs[r.binary()] = r
            return pickle.dumps({"refs": [r.binary() for r in out_list]})

        if method == "GetActor":
            handle = await blocking(
                ray_tpu.get_actor, req["name"], req.get("namespace"))
            sess.actors[handle.actor_id.binary()] = handle
            return pickle.dumps({
                "actor_id": handle.actor_id.binary(),
                "methods": handle._method_names,
                "class_name": handle._class_name,
            })

        if method == "KillActor":
            handle = sess.actors.get(req["actor_id"])
            if handle is not None:
                await blocking(ray_tpu.kill, handle,
                               no_restart=req.get("no_restart", True))
                sess.owned_actors.pop(req["actor_id"], None)
            return pickle.dumps({"status": "ok"})

        if method == "ClusterInfo":
            return pickle.dumps({
                "cluster_resources": await blocking(ray_tpu.cluster_resources),
                "available_resources": await blocking(ray_tpu.available_resources),
                "nodes": await blocking(ray_tpu.nodes),
            })

        if method == "ReleaseRefs":
            refs = [sess.refs.pop(r, None) for r in req["refs"]]
            refs = [r for r in refs if r is not None]
            if refs:
                try:
                    from ray_tpu._private.worker import global_worker

                    await blocking(global_worker().free_objects, refs)
                except Exception:
                    pass
            return pickle.dumps({"released": len(refs)})

        if method == "Ping":
            return pickle.dumps({"ok": True})

        raise ValueError(f"client proxy: unknown method {method}")

    def _rebuild_args(self, sess, blob):
        """Client-side refs arrive as markers; swap in the proxy's refs."""
        args, kwargs = loads_trusted(blob)

        def fix(v):
            if isinstance(v, _RefMarker):
                return sess.refs[v.ref_id]
            return v

        return [fix(a) for a in args], {k: fix(v) for k, v in kwargs.items()}


class _RefMarker:
    __slots__ = ("ref_id",)

    def __init__(self, ref_id: bytes):
        self.ref_id = ref_id

    def __reduce__(self):
        return (_RefMarker, (self.ref_id,))


def start_client_server(port: int = 10001, host: str = "0.0.0.0",
                        address: Optional[str] = None):
    """Run a client proxy (blocking). Connects to the cluster first when
    ``address`` is given, else expects ray_tpu to already be initialized."""
    if not ray_tpu.is_initialized():
        ray_tpu.init(address=address)

    async def run():
        proxy = ClientProxyServer(host, port)
        addr = await proxy.start()
        print(f"ray-tpu client server listening on {addr}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())

from ray_tpu.util.client.server import ClientProxyServer, start_client_server

__all__ = ["ClientProxyServer", "start_client_server"]

"""Thin external client for ``ray-tpu://`` addresses.

Reference: python/ray/util/client/worker.py — the client-mode Worker that
ships pickled operations to the in-cluster proxy and wraps returned ids
as refs/handles. ``ray_tpu.init(address="ray-tpu://host:port")`` installs
a :class:`ClientWorker` as the global worker; the whole public API
(remote/get/put/wait/actors) works unchanged from outside the cluster.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import threading
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu._private.serialization import loads_trusted
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.rpc import RetryingRpcClient
from ray_tpu.exceptions import TaskError
from ray_tpu.object_ref import ObjectRef


def _options_dict(opts) -> Dict[str, Any]:
    """Non-default dataclass fields -> kwargs for .options() on the proxy."""
    out = {}
    for f in dataclasses.fields(opts):
        value = getattr(opts, f.name)
        if f.default is not dataclasses.MISSING:
            default = f.default
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore
            default = f.default_factory()  # type: ignore
        else:
            default = None
        if value != default:
            out[f.name] = value
    return out


class ClientWorker:
    """Global-worker implementation that proxies everything over one TCP
    connection to an in-cluster ClientProxyServer."""

    mode = "client"

    def __init__(self, address: str, namespace: str = "default"):
        import uuid

        # ray-tpu://host:port
        hostport = address.split("://", 1)[1]
        self.address = hostport
        self.namespace = namespace
        self.job_id = JobID.from_int(1)
        # session id rides every request so a transparent reconnect resumes
        # the same proxy-side session (refs/actors survive TCP blips)
        self.session_id = f"client_{uuid.uuid4().hex[:12]}"
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self.loop.run_forever, daemon=True, name="ray-tpu-client")
        self._thread.start()
        self.client = RetryingRpcClient(hostport)
        try:
            self._call("Ping", {}, timeout=30.0)
        except BaseException:
            # don't leak the loop thread when the endpoint is unreachable
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._thread.join(timeout=5)
            raise

    # -- plumbing ------------------------------------------------------

    # ops with side effects must not be blindly re-sent after a
    # post-delivery connection drop (double execution); pre-send failures
    # still retry safely inside RetryingRpcClient
    _MUTATING = ("Put", "SubmitTask", "CreateActor", "SubmitActorTask",
                 "KillActor")

    def _call(self, method: str, req: dict, timeout: Optional[float] = None):
        import pickle

        req = dict(req, session=self.session_id)
        retries = 0 if method in self._MUTATING else None
        fut = asyncio.run_coroutine_threadsafe(
            self.client.call(method, pickle.dumps(req),
                             timeout=timeout or 300.0, retries=retries),
            self.loop)
        # the proxy is inside the user's own trust domain; still route the
        # unpickle through the audited boundary
        return loads_trusted(fut.result(timeout=(timeout or 300.0) + 30))

    @staticmethod
    def _marker_args(args, kwargs) -> bytes:
        from ray_tpu.util.client.server import _RefMarker

        def fix(v):
            if isinstance(v, ObjectRef):
                return _RefMarker(v.binary())
            return v

        return cloudpickle.dumps(
            ([fix(a) for a in args], {k: fix(v) for k, v in kwargs.items()}))

    @staticmethod
    def _mk_refs(binaries: List[bytes]) -> List[ObjectRef]:
        return [ObjectRef(ObjectID(b)) for b in binaries]

    # -- objects -------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        reply = self._call("Put", {"blob": cloudpickle.dumps(value)})
        return ObjectRef(ObjectID(reply["ref"]))

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        reply = self._call("Get", {
            "refs": [r.binary() for r in ref_list],
            "timeout": timeout,
        }, timeout=(timeout or 86400.0) + 10)
        if reply["status"] == "error":
            raise loads_trusted(reply["error"])
        values = loads_trusted(reply["blob"])
        return values[0] if single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        reply = self._call("Wait", {
            "refs": [r.binary() for r in refs],
            "num_returns": num_returns, "timeout": timeout,
        }, timeout=(timeout or 86400.0) + 10)
        by_id = {r.binary(): r for r in refs}
        return ([by_id[b] for b in reply["ready"]],
                [by_id[b] for b in reply["pending"]])

    def free_objects(self, refs):
        """Explicit release on the proxy (automatic finalizer-driven GC is
        deferred; the session grace-reaper is the backstop)."""
        try:
            self._call("ReleaseRefs", {"refs": [r.binary() for r in refs]})
        except Exception:
            pass

    # -- tasks ---------------------------------------------------------

    def submit_task(self, remote_fn, args, kwargs, opts):
        blob = cloudpickle.dumps(remote_fn.function)
        fn_hash = hashlib.sha1(blob).hexdigest()
        reply = self._call("SubmitTask", {
            "fn_hash": fn_hash, "fn_blob": blob,
            "args_blob": self._marker_args(args, kwargs),
            "options": _options_dict(opts),
        })
        refs = self._mk_refs(reply["refs"])
        return refs[0] if len(refs) == 1 else refs

    # -- actors --------------------------------------------------------

    def create_actor(self, actor_cls, args, kwargs, opts):
        from ray_tpu.actor import ActorHandle

        blob = cloudpickle.dumps(actor_cls.cls)
        cls_hash = hashlib.sha1(blob).hexdigest()
        reply = self._call("CreateActor", {
            "cls_hash": cls_hash, "cls_blob": blob,
            "args_blob": self._marker_args(args, kwargs),
            "options": _options_dict(opts),
        })
        return ActorHandle(ActorID(reply["actor_id"]), reply["methods"],
                           reply["class_name"])

    def submit_actor_task(self, handle, method_name, args, kwargs,
                          num_returns=1, tensor_transport=""):
        options = {}
        if num_returns != 1:
            options["num_returns"] = num_returns
        if tensor_transport:
            options["tensor_transport"] = tensor_transport
        reply = self._call("SubmitActorTask", {
            "actor_id": handle.actor_id.binary(), "method": method_name,
            "args_blob": self._marker_args(args, kwargs),
            "options": options,
        })
        refs = self._mk_refs(reply["refs"])
        return refs[0] if len(refs) == 1 else refs

    def get_actor(self, name: str, namespace: Optional[str] = None):
        from ray_tpu.actor import ActorHandle

        reply = self._call("GetActor", {"name": name,
                                        "namespace": namespace or self.namespace})
        return ActorHandle(ActorID(reply["actor_id"]), reply["methods"],
                           reply["class_name"])

    def kill_actor(self, handle, no_restart=True):
        self._call("KillActor", {"actor_id": handle.actor_id.binary(),
                                 "no_restart": no_restart})

    def cancel(self, ref, force=False, recursive=True):
        import logging

        logging.getLogger("ray_tpu").warning(
            "ray_tpu.cancel() is not supported in client mode yet; the "
            "task keeps running")

    # -- cluster info --------------------------------------------------

    def cluster_resources(self):
        return self._call("ClusterInfo", {})["cluster_resources"]

    def available_resources(self):
        return self._call("ClusterInfo", {})["available_resources"]

    def nodes(self):
        return self._call("ClusterInfo", {})["nodes"]

    # -- futures (rarely used from external clients) -------------------

    def as_future(self, ref):
        import concurrent.futures
        import threading as _th

        fut: "concurrent.futures.Future" = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(self.get(ref))
            except Exception as e:
                fut.set_exception(e)

        _th.Thread(target=_resolve, daemon=True).start()
        return fut

    async def await_ref(self, ref):
        # never block the caller's event loop on the round-trip
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self.get, ref)

    def shutdown(self):
        try:
            asyncio.run_coroutine_threadsafe(
                self.client.close(), self.loop).result(timeout=5)
        except Exception:
            pass
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)

"""Scheduling strategies (reference: python/ray/util/scheduling_strategies.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str  # hex node id
    soft: bool = False


@dataclass
class NodeLabelSchedulingStrategy:
    """Schedule onto a node whose labels match (reference:
    src/ray/raylet/scheduling/policy/node_label_scheduling_policy.cc)."""

    hard: Dict[str, str] = field(default_factory=dict)
    soft: Dict[str, str] = field(default_factory=dict)


@dataclass
class SpreadSchedulingStrategy:
    pass


DEFAULT = "DEFAULT"
SPREAD = "SPREAD"

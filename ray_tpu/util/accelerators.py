"""TPU accelerator detection (reference: python/ray/_private/accelerators/tpu.py).

Detects chips per host and slice metadata so the raylet can advertise
``TPU`` resources and slice labels (``TPUAcceleratorManager`` at tpu.py:267,
pod-type inference :151). Detection order:

1. explicit env overrides (``RAY_TPU_CHIPS``, ``TPU_VISIBLE_CHIPS``),
2. GCE TPU-VM environment variables (``TPU_ACCELERATOR_TYPE``,
   ``TPU_WORKER_ID``, set by the TPU runtime on real TPU VMs),
3. jax device enumeration — only when ``RAY_TPU_DETECT_TPU=1``, because
   importing jax is slow and must not happen in the raylet by default.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

from ray_tpu._private.common import (
    LABEL_TPU_POD_TYPE,
    LABEL_TPU_SLICE,
    LABEL_TPU_TOPOLOGY,
    LABEL_TPU_WORKER_ID,
)


def _chips_for_accelerator_type(acc_type: str) -> int:
    """Chips on THIS host for a slice of the given type (e.g. 'v5litepod-16').

    v5e/v6e hosts have up to 4 chips (8 for v4/v5p with 4 dual-core chips);
    a host never has more chips than the slice total.
    """
    try:
        total = int(acc_type.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 0
    gen = acc_type.split("-")[0].lower()
    per_host = 4
    if gen in ("v2", "v3"):
        per_host = 8
    return min(total, per_host)


def detect_tpu() -> Tuple[int, Dict[str, str]]:
    """Returns (num_chips_on_host, labels)."""
    labels: Dict[str, str] = {}
    env_chips = os.environ.get("RAY_TPU_CHIPS") or os.environ.get("TPU_VISIBLE_CHIPS")
    acc_type = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    slice_name = (
        os.environ.get("RAY_TPU_SLICE_NAME")
        or os.environ.get("TPU_NAME")
        or os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")[0]
    )
    worker_id = os.environ.get("TPU_WORKER_ID", "")
    topology = os.environ.get("TPU_TOPOLOGY", "")

    chips = 0
    if env_chips:
        try:
            chips = len(env_chips.split(",")) if "," in env_chips else int(env_chips)
        except ValueError:
            chips = 0
    elif acc_type:
        chips = _chips_for_accelerator_type(acc_type)
    elif os.environ.get("RAY_TPU_DETECT_TPU") == "1":
        try:
            import jax

            devices = [d for d in jax.devices() if d.platform == "tpu"]
            chips = len(devices)
            if devices and not acc_type:
                acc_type = getattr(devices[0], "device_kind", "tpu")
        except Exception:
            chips = 0

    if chips:
        if slice_name:
            labels[LABEL_TPU_SLICE] = slice_name
        if acc_type:
            labels[LABEL_TPU_POD_TYPE] = acc_type
        if worker_id:
            labels[LABEL_TPU_WORKER_ID] = worker_id
        if topology:
            labels[LABEL_TPU_TOPOLOGY] = topology
    return chips, labels


def num_tpu_chips_on_host() -> int:
    return detect_tpu()[0]

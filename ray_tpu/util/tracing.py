"""Causal task tracing + profile events with chrome://tracing export.

Reference: the reference captures per-task profile events in C++
(``core_worker/profile_event.cc``) into a ``TaskEventBuffer``
(``task_event_buffer.cc``) that flushes to the GCS ``GcsTaskManager`` and
feeds the dashboard timeline; opt-in OpenTelemetry spans wrap remote calls
and propagate trace context through the TaskSpec
(``util/tracing/tracing_helper.py:326``). Here every worker buffers span
records and flushes them to the GCS KV (``trace`` namespace); the driver
gathers them with :func:`get_spans` and writes a chrome://tracing JSON
timeline with :func:`export_chrome_trace` (also ``ray-tpu timeline``).

Causality: every span carries ``trace_id``/``span_id``/``parent_id``.
The active span rides a :mod:`contextvars` context var; ``submit_task`` /
actor submission stamp the caller's active span into the ``TaskSpec``
(``trace_id``/``parent_span_id``), and task execution installs the task's
span as current, so nested ``.remote()`` calls and :func:`profile` blocks
form a tree that spans processes. :func:`export_chrome_trace` emits
chrome-trace flow events (``ph: "s"/"f"``) for every cross-thread edge, so
driver→actor→nested-task causality renders as arrows in Perfetto.

Enable with ``RAY_TPU_ENABLE_TRACING=1`` (on the driver: before init — the
flag propagates to workers through the runtime env) or per-session via
``ray_tpu.util.tracing.enable()``. User code can add custom spans::

    with ray_tpu.util.tracing.profile("tokenize"):
        ...
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import json
import os
from ray_tpu._private import wire
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

_lock = threading.Lock()
_buffer: List[dict] = []
_flush_counter = 0
_enabled: Optional[bool] = None

_FLUSH_EVERY = 32
_FLUSH_INTERVAL_S = 1.0
_MAX_BUFFER = 10_000  # drop-oldest beyond this: tracing never leaks unbounded
_last_flush = time.time()
_timer: Optional[threading.Timer] = None
# cluster-unique flush-key tag (pids collide across nodes/restarts)
_proc_tag = uuid.uuid4().hex[:10]

# ---------------------------------------------------------------------------
# trace context (reference: tracing_helper.py's _opentelemetry context
# propagation — here a plain (trace_id, span_id) pair on a ContextVar, so it
# follows asyncio tasks automatically and can be installed on pool threads)
# ---------------------------------------------------------------------------

_ctx: "contextvars.ContextVar[Optional[Tuple[str, str]]]" = \
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None)


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def current_context() -> Optional[Tuple[str, str]]:
    """The active (trace_id, span_id), or None outside any span."""
    return _ctx.get()


def set_context(trace_id: str, span_id: str):
    """Install (trace_id, span_id) as the active span; returns a token for
    :func:`reset_context`. Used by task execution so nested ``.remote()``
    calls and :func:`profile` blocks parent onto the running task's span."""
    return _ctx.set((trace_id, span_id))


def reset_context(token) -> None:
    try:
        _ctx.reset(token)
    except ValueError:
        # token from another context (e.g. exec-pool thread reuse): clearing
        # is the right fallback — never let a stale span leak across tasks
        _ctx.set(None)


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RAY_TPU_ENABLE_TRACING", "") in ("1", "true")
    return _enabled


def enable():
    global _enabled
    os.environ["RAY_TPU_ENABLE_TRACING"] = "1"
    _enabled = True


def reset_after_fork():
    """Drop every piece of state a forked child inherits from its parent's
    span pipeline. A zygote-forked worker shares the parent's buffer, flush
    counter, proc tag, AND a dangling ``_timer`` reference (the timer
    thread does not survive the fork, so the child would believe a flush
    is armed and never arm one again) — without this reset the child
    re-ships the zygote's buffered spans and clobbers the parent's GCS
    flush keys (same class of bug as core_worker's ``_obs_proc_tag``)."""
    global _timer, _flush_counter, _last_flush, _proc_tag
    with _lock:
        _buffer.clear()
        _timer = None  # parent's timer thread is gone in the child
        _flush_counter = 0
        _last_flush = time.time()
        _proc_tag = uuid.uuid4().hex[:10]
    del _local_spans[:]
    _ctx.set(None)


# -- tail-span protection: without this, spans recorded in the last
# _FLUSH_INTERVAL_S before process exit die with the pending _timer --
_atexit_registered = False


def _flush_at_exit():
    try:
        flush()
    except Exception:
        pass
    try:
        from ray_tpu._private import task_events

        task_events.flush()
    except Exception:
        pass


def _ensure_atexit():
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(_flush_at_exit)


def record_span(name: str, start_s: float, end_s: float,
                category: str = "task", **extra):
    """Buffer one span; flushes to the GCS every _FLUSH_EVERY spans.

    Span causality fields (``trace_id``/``span_id``/``parent_id``) are
    filled from the active context when not passed explicitly."""
    if not enabled():
        return
    if "trace_id" not in extra:
        ctx = _ctx.get()
        if ctx is not None:
            extra["trace_id"] = ctx[0]
            extra.setdefault("parent_id", ctx[1])
    span = {
        "name": name,
        "cat": category,
        "ts": start_s,
        "dur": end_s - start_s,
        "pid": os.getpid(),
        "tid": threading.get_ident() % 100_000,
        **extra,
    }
    global _timer
    flush_now = False
    with _lock:
        _ensure_atexit()
        _buffer.append(span)
        if len(_buffer) > _MAX_BUFFER:
            del _buffer[: len(_buffer) - _MAX_BUFFER]
        if len(_buffer) >= _FLUSH_EVERY:
            # size-triggered flushes hand off without waiting for the GCS
            # round trip (a traced submit loop must not stall every 32
            # spans); boundedness comes from _MAX_BUFFER drop-oldest
            flush_now = True
        elif _timer is None:
            _timer = threading.Timer(_FLUSH_INTERVAL_S, _timer_flush)
            _timer.daemon = True
            _timer.start()
    if flush_now:
        flush(block=False)


def _timer_flush():
    global _timer
    with _lock:
        _timer = None
    flush()


@contextlib.contextmanager
def profile(name: str, category: str = "user", **extra):
    """Custom user span (reference: ray.util.tracing via profile events).

    Runs as a child of the active span (the executing task, or an enclosing
    profile block) and installs itself as current for the duration, so
    nested profile blocks and nested ``.remote()`` submissions tree up."""
    if not enabled():
        yield
        return
    parent = _ctx.get()
    span_id = new_span_id()
    trace_id = parent[0] if parent is not None else new_trace_id()
    token = _ctx.set((trace_id, span_id))
    t0 = time.time()
    try:
        yield
    finally:
        reset_context(token)
        record_span(name, t0, time.time(), category=category,
                    trace_id=trace_id, span_id=span_id,
                    parent_id=parent[1] if parent is not None else None,
                    **extra)


def flush(block: bool = True):
    """Push buffered spans to the GCS KV; safe to call anywhere.

    ``block=False`` (the size-triggered path in :func:`record_span`) ships
    without waiting for the round trip; explicit callers (get_spans,
    shutdown, atexit) keep the blocking read-your-writes semantics."""
    global _flush_counter, _last_flush
    with _lock:
        _last_flush = time.time()
        if not _buffer:
            return
        spans, _buffer[:] = list(_buffer), []
        _flush_counter += 1
        counter = _flush_counter
    def _rebuffer():
        with _lock:
            _buffer[:0] = spans
            if len(_buffer) > _MAX_BUFFER:
                del _buffer[: len(_buffer) - _MAX_BUFFER]

    try:
        from ray_tpu._private.worker import global_worker, is_initialized

        if not is_initialized():
            _rebuffer()  # pre-init spans surface after init
            return
        core = global_worker()
        if getattr(core, "mode", "") == "local":
            # local mode: keep spans in-process (get_spans reads them back)
            _local_spans.extend(spans)
            return
        req = {"ns": "trace", "key": f"spans_{_proc_tag}_{counter}",
               "value": wire.dumps(spans)}

        async def _put_guarded():
            try:
                await core._gcs_call("KVPut", req)
            except Exception:
                _rebuffer()

        try:
            import asyncio

            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is not None and running is core.loop:
            # called from the worker's event loop (task-execution path):
            # blocking would deadlock — fire and forget, re-buffer on error
            from ray_tpu._private.async_util import spawn

            spawn(_put_guarded(), what="trace-span flush")
        elif not block:
            # async hand-off: ship on the io loop, don't await the ack
            # (_put_guarded re-buffers on failure)
            import asyncio as _asyncio

            _asyncio.run_coroutine_threadsafe(_put_guarded(), core.loop)
        else:
            core._run(_put_guarded())
    except Exception:
        # tracing must never take down the workload
        _rebuffer()


_local_spans: List[dict] = []


def get_spans() -> List[dict]:
    """Gather all spans recorded so far, cluster-wide."""
    flush()
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    if getattr(core, "mode", "") == "local":
        return list(_local_spans)
    keys = core._run(core._gcs_call(
        "KVKeys", {"ns": "trace", "prefix": "spans_"}))["keys"]
    out: List[dict] = []
    for key in keys:
        blob = core._run(core._gcs_call(
            "KVGet", {"ns": "trace", "key": key}))["value"]
        if blob:
            out.extend(wire.loads(blob))
    return sorted(out, key=lambda s: s["ts"])


def clear():
    """Delete all collected spans (GCS trace table + local buffers)."""
    global _local_spans
    with _lock:
        _buffer.clear()
    _local_spans = []
    from ray_tpu._private.worker import global_worker, is_initialized

    if not is_initialized():
        return
    core = global_worker()
    if getattr(core, "mode", "") == "local":
        return
    core._run(core._gcs_call("KVDel", {"ns": "trace", "key": "spans_",
                                       "prefix": True}))


_SPAN_META = ("name", "cat", "ts", "dur", "pid", "tid")


def spans_to_chrome_events(spans: List[dict],
                           flow_id_base: int = 0) -> List[dict]:
    """Convert span records to chrome-trace events (``ph: "X"`` slices +
    flow-event pairs for cross-track parent→child edges). Shared by the
    driver-side :func:`export_chrome_trace` and the GCS timeline endpoint
    (``GET /api/timeline``), which merges these with task-event slices."""
    events = [
        {
            "name": s["name"],
            "cat": s.get("cat", "task"),
            "ph": "X",
            "ts": s["ts"] * 1e6,  # microseconds
            "dur": max(s["dur"], 0.0) * 1e6,
            "pid": s.get("pid", 0),
            "tid": s.get("tid", 0),
            "args": {k: v for k, v in s.items() if k not in _SPAN_META},
        }
        for s in spans
    ]
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    flow_n = flow_id_base
    for s in spans:
        parent = by_id.get(s.get("parent_id") or "")
        if parent is None:
            continue
        same_track = (parent.get("pid"), parent.get("tid")) == \
            (s.get("pid"), s.get("tid"))
        if same_track:
            continue  # same-thread nesting already renders as stacked slices
        flow_n += 1
        # the flow-start ts must land inside the parent slice for Perfetto
        # to bind the arrow to it
        start_ts = min(max(s["ts"], parent["ts"]),
                       parent["ts"] + max(parent["dur"], 0.0))
        events.append({
            "name": "task_flow", "cat": "flow", "ph": "s", "id": flow_n,
            "ts": start_ts * 1e6, "pid": parent.get("pid", 0),
            "tid": parent.get("tid", 0),
        })
        events.append({
            "name": "task_flow", "cat": "flow", "ph": "f", "bp": "e",
            "id": flow_n, "ts": s["ts"] * 1e6, "pid": s.get("pid", 0),
            "tid": s.get("tid", 0),
        })
    return events


def export_chrome_trace(path: str) -> int:
    """Write a chrome://tracing (about://tracing, Perfetto) JSON file.

    Besides the ``ph: "X"`` duration slices, every parent→child span edge
    that crosses a thread or process emits a flow-event pair (``ph: "s"`` on
    the parent slice, ``ph: "f"`` on the child slice) so cross-process
    causality — driver submit → actor task → nested task — renders as
    arrows. Returns the number of events written."""
    events = spans_to_chrome_events(get_spans())
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)

"""Task tracing + profile events with chrome://tracing export.

Reference: the reference captures per-task profile events in C++
(``core_worker/profile_event.cc``) into a ``TaskEventBuffer``
(``task_event_buffer.cc``) that flushes to the GCS ``GcsTaskManager`` and
feeds the dashboard timeline; opt-in OpenTelemetry spans wrap remote calls
(``util/tracing/tracing_helper.py:326``). Here every worker buffers span
records and flushes them to the GCS KV (``trace`` namespace); the driver
gathers them with :func:`get_spans` and writes a chrome://tracing JSON
timeline with :func:`export_chrome_trace` (also ``ray-tpu timeline``).

Enable with ``RAY_TPU_ENABLE_TRACING=1`` (on the driver: before init — the
flag propagates to workers through the runtime env) or per-session via
``ray_tpu.util.tracing.enable()``. User code can add custom spans::

    with ray_tpu.util.tracing.profile("tokenize"):
        ...
"""

from __future__ import annotations

import contextlib
import json
import os
from ray_tpu._private import wire
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_buffer: List[dict] = []
_flush_counter = 0
_enabled: Optional[bool] = None

_FLUSH_EVERY = 32
_FLUSH_INTERVAL_S = 1.0
_MAX_BUFFER = 10_000  # drop-oldest beyond this: tracing never leaks unbounded
_last_flush = time.time()
_timer: Optional[threading.Timer] = None
# cluster-unique flush-key tag (pids collide across nodes/restarts)
_proc_tag = uuid.uuid4().hex[:10]


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RAY_TPU_ENABLE_TRACING", "") in ("1", "true")
    return _enabled


def enable():
    global _enabled
    os.environ["RAY_TPU_ENABLE_TRACING"] = "1"
    _enabled = True


def record_span(name: str, start_s: float, end_s: float,
                category: str = "task", **extra):
    """Buffer one span; flushes to the GCS every _FLUSH_EVERY spans."""
    if not enabled():
        return
    span = {
        "name": name,
        "cat": category,
        "ts": start_s,
        "dur": end_s - start_s,
        "pid": os.getpid(),
        "tid": threading.get_ident() % 100_000,
        **extra,
    }
    global _timer
    flush_now = False
    with _lock:
        _buffer.append(span)
        if len(_buffer) > _MAX_BUFFER:
            del _buffer[: len(_buffer) - _MAX_BUFFER]
        if len(_buffer) >= _FLUSH_EVERY:
            # size-triggered flushes are synchronous (backpressure);
            # time-triggered ones run on the timer thread so sporadic user
            # spans never pay a GCS round-trip inline
            flush_now = True
        elif _timer is None:
            _timer = threading.Timer(_FLUSH_INTERVAL_S, _timer_flush)
            _timer.daemon = True
            _timer.start()
    if flush_now:
        flush()


def _timer_flush():
    global _timer
    with _lock:
        _timer = None
    flush()


@contextlib.contextmanager
def profile(name: str, category: str = "user", **extra):
    """Custom user span (reference: ray.util.tracing via profile events)."""
    t0 = time.time()
    try:
        yield
    finally:
        record_span(name, t0, time.time(), category=category, **extra)


def flush():
    """Push buffered spans to the GCS KV; safe to call anywhere."""
    global _flush_counter, _last_flush
    with _lock:
        _last_flush = time.time()
        if not _buffer:
            return
        spans, _buffer[:] = list(_buffer), []
        _flush_counter += 1
        counter = _flush_counter
    def _rebuffer():
        with _lock:
            _buffer[:0] = spans
            if len(_buffer) > _MAX_BUFFER:
                del _buffer[: len(_buffer) - _MAX_BUFFER]

    try:
        from ray_tpu._private.worker import global_worker, is_initialized

        if not is_initialized():
            _rebuffer()  # pre-init spans surface after init
            return
        core = global_worker()
        if getattr(core, "mode", "") == "local":
            # local mode: keep spans in-process (get_spans reads them back)
            _local_spans.extend(spans)
            return
        req = {"ns": "trace", "key": f"spans_{_proc_tag}_{counter}",
               "value": wire.dumps(spans)}

        async def _put_guarded():
            try:
                await core._gcs_call("KVPut", req)
            except Exception:
                _rebuffer()

        try:
            import asyncio

            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is not None and running is core.loop:
            # called from the worker's event loop (task-execution path):
            # blocking would deadlock — fire and forget, re-buffer on error
            asyncio.ensure_future(_put_guarded())
        else:
            core._run(_put_guarded())
    except Exception:
        # tracing must never take down the workload
        _rebuffer()


_local_spans: List[dict] = []


def get_spans() -> List[dict]:
    """Gather all spans recorded so far, cluster-wide."""
    flush()
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    if getattr(core, "mode", "") == "local":
        return list(_local_spans)
    keys = core._run(core._gcs_call(
        "KVKeys", {"ns": "trace", "prefix": "spans_"}))["keys"]
    out: List[dict] = []
    for key in keys:
        blob = core._run(core._gcs_call(
            "KVGet", {"ns": "trace", "key": key}))["value"]
        if blob:
            out.extend(wire.loads(blob))
    return sorted(out, key=lambda s: s["ts"])


def clear():
    """Delete all collected spans (GCS trace table + local buffers)."""
    global _local_spans
    with _lock:
        _buffer.clear()
    _local_spans = []
    from ray_tpu._private.worker import global_worker, is_initialized

    if not is_initialized():
        return
    core = global_worker()
    if getattr(core, "mode", "") == "local":
        return
    core._run(core._gcs_call("KVDel", {"ns": "trace", "key": "spans_",
                                       "prefix": True}))


def export_chrome_trace(path: str) -> int:
    """Write a chrome://tracing (about://tracing, Perfetto) JSON file.
    Returns the number of events written."""
    spans = get_spans()
    events = [
        {
            "name": s["name"],
            "cat": s.get("cat", "task"),
            "ph": "X",
            "ts": s["ts"] * 1e6,  # microseconds
            "dur": max(s["dur"], 0.0) * 1e6,
            "pid": s.get("pid", 0),
            "tid": s.get("tid", 0),
            "args": {k: v for k, v in s.items()
                     if k not in ("name", "cat", "ts", "dur", "pid", "tid")},
        }
        for s in spans
    ]
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)

"""Structured cluster events (reference: src/ray/util/event.cc + the
export-event pipeline src/ray/protobuf/public/events_*.proto -> dashboard
aggregator): system components report typed events (node/actor/worker
lifecycle, OOM kills) to the GCS, which keeps a bounded ring and publishes
them on the ``events`` pubsub channel; ``ray-tpu events`` and
``list_events()`` read them back."""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")

# local-mode fallback ring (mirrors tracing.py's local tier)
_local_events: List[Dict[str, Any]] = []


def _on_worker_loop(core) -> bool:
    try:
        import asyncio

        return asyncio.get_running_loop() is core.loop
    except RuntimeError:
        return False


def record(source: str, severity: str, message: str, **metadata) -> None:
    """Report one structured event to the GCS (best-effort, never raises).
    Safe from any context: driver threads, sync tasks, and async actor
    methods (which run ON the worker's io loop — those fire and forget)."""
    from ray_tpu._private.worker import global_worker, is_initialized

    severity = severity.upper()
    if severity not in SEVERITIES:
        severity = "INFO"
    event = {"ts": time.time(), "source": source, "severity": severity,
             "message": message, "metadata": metadata}
    try:
        if not is_initialized():
            _local_events.append(event)
            return
        core = global_worker()
        if getattr(core, "mode", "") == "local":
            _local_events.append(event)
            return
        coro = core._gcs_call("ReportEvent", {"event": event})
        if _on_worker_loop(core):
            from ray_tpu._private.async_util import spawn

            spawn(coro, what="event report")
        else:
            core._run(coro, 10.0)
    except Exception:
        pass


def list_events(source: Optional[str] = None,
                severity: Optional[str] = None,
                limit: int = 200) -> List[Dict[str, Any]]:
    """Recent cluster events, newest last."""
    from ray_tpu._private.worker import global_worker

    severity = severity.upper() if severity else None
    core = global_worker()
    if getattr(core, "mode", "") == "local" or not hasattr(core, "_gcs_call"):
        out = list(_local_events)
        if source:
            out = [e for e in out if e.get("source") == source]
        if severity:
            out = [e for e in out if e.get("severity") == severity]
        return out[-limit:]
    out = core._run(core._gcs_call("GetEvents", {
        "source": source, "severity": severity, "limit": limit}), 30.0)
    return out["events"]

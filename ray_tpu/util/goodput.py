"""Goodput ledger: exclusive, exhaustive wall-clock attribution.

Every process classifies its wall time into the buckets below via the
:func:`region` context manager (nested regions are EXCLUSIVE: a child
region's time is subtracted from its parent, so each second lands in
exactly one bucket) plus :func:`add` for externally-measured windows
(e.g. the train controller's re-form downtime). Whatever is not claimed
by any bucket is derived as ``idle`` in :func:`snapshot`, making the
decomposition exhaustive by construction: ``sum(buckets) + idle ==
wall``.

The ledger is per-process and per-job (:func:`set_job` re-anchors when
the job tag changes). The core worker's observability flush ships
:func:`flush_payload` into the GCS KV ``goodput`` namespace on the same
cadence as the metrics registry; the GCS aggregates the per-process
payloads into a per-job ``GoodputLedger`` surfaced as ``/api/goodput``,
``util.state.goodput()`` and ``ray-tpu goodput``, and mirrors
``goodput_fraction`` / MFU into the metrics registry so they ride
``MetricsHistory`` like any other gauge.

Signal sources wired into the region API:

- ``step_compute`` / ``compile``: ``parallel/train.py`` wraps the train
  step dispatch; a :class:`CompileWatch` keyed on batch shapes/dtypes
  detects jit cache misses and routes the blocking first call into the
  ``compile`` bucket (counting *re*-compiles — same program, new key —
  separately as the storm signal);
- ``input_stall``: ``data/dataset.py:iter_device_batches`` wraps the
  consumer-side queue wait;
- ``ckpt_pause``: ``ckpt/saver.py`` wraps the caller-thread
  drain+snapshot window of ``CheckpointSaver.save``;
- ``reform_downtime``: the elastic train controller's RESTARTING window
  and pipeline gang recovery report via :func:`add`;
- ``bubble`` / ``collective_wait``: pipeline stages report schedule
  recv waits and send/reduce waits via :func:`add`;
- ``overhead``: the core worker's observability flush itself.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "BUCKETS", "CompileWatch", "add", "batch_key", "count", "enabled",
    "flush_payload", "note_mfu", "region", "reset", "reset_after_fork",
    "set_job", "snapshot",
]

#: Exclusive attribution buckets; ``idle`` is derived (wall minus the
#: sum of these) so the decomposition is exhaustive by construction.
BUCKETS = (
    "step_compute", "collective_wait", "input_stall", "ckpt_pause",
    "compile", "reform_downtime", "bubble", "overhead",
)

_lock = threading.Lock()
_tls = threading.local()

_job: str = ""
_anchor: Optional[float] = None  # perf_counter at ledger start
_anchor_ts: float = 0.0          # time.time() at ledger start
_buckets: Dict[str, float] = {}
_counters: Dict[str, float] = {}
_mfu: Optional[float] = None

_metrics_lock = threading.Lock()
_metrics: Optional[dict] = None


def enabled() -> bool:
    from ray_tpu._private.config import RAY_CONFIG

    return bool(RAY_CONFIG.goodput_enabled)


def _obs() -> dict:
    """Lazily-created goodput instruments on the shared metrics registry
    (set on every ledger flush, so ``goodput_fraction`` and MFU ride
    ``MetricsHistory`` like any other gauge)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Gauge

            _metrics = {
                "fraction": Gauge(
                    "ray_tpu.goodput.fraction",
                    "step_compute share of ledger wall time for this "
                    "process's active job"),
                "mfu": Gauge(
                    "ray_tpu.goodput.mfu",
                    "model FLOPs utilization last reported by the train "
                    "loop on this process"),
                "compiles": Gauge(
                    "ray_tpu.goodput.compiles",
                    "cumulative jit compiles observed by the compile "
                    "watch (first-key compiles plus recompiles)"),
                "recompiles": Gauge(
                    "ray_tpu.goodput.recompiles",
                    "cumulative shape/dtype-keyed jit RE-compiles (same "
                    "program, new key) — the recompile-storm signal"),
                "bucket_seconds": Gauge(
                    "ray_tpu.goodput.bucket_seconds",
                    "cumulative attributed wall seconds per goodput "
                    "bucket", tag_keys=("bucket",)),
            }
        return _metrics


def _anchor_locked() -> None:
    global _anchor, _anchor_ts
    if _anchor is None:
        _anchor = time.perf_counter()
        _anchor_ts = time.time()


def set_job(name: str) -> None:
    """Tag this process's ledger with its job (run) name. A *different*
    job name resets the accumulators and re-anchors wall time, so a
    reused worker never leaks a previous job's seconds into the next."""
    global _job, _anchor, _anchor_ts, _mfu
    if not enabled():
        return
    with _lock:
        if name != _job:
            _buckets.clear()
            _counters.clear()
            _mfu = None
            _anchor = None
        _job = name
        _anchor_locked()


def _add_locked(bucket: str, seconds: float) -> None:
    _anchor_locked()
    _buckets[bucket] = _buckets.get(bucket, 0.0) + seconds


def add(bucket: str, seconds: float) -> None:
    """Attribute an externally-measured window (controller re-form
    downtime, pipeline bubble/reduce waits) directly to a bucket."""
    if not enabled() or seconds <= 0.0:
        return
    with _lock:
        _add_locked(bucket, float(seconds))


def count(name: str, n: float = 1) -> None:
    """Bump a ledger counter (steps, compiles, recompiles, input_waits,
    ckpt_saves, reforms)."""
    if not enabled():
        return
    with _lock:
        _anchor_locked()
        _counters[name] = _counters.get(name, 0) + n


def note_mfu(value: float) -> None:
    """Record the train loop's latest MFU so it rides the ledger flush
    (and the ``ray_tpu.goodput.mfu`` gauge) without a separate path."""
    global _mfu
    if not enabled():
        return
    with _lock:
        _anchor_locked()
        _mfu = float(value)


@contextmanager
def region(bucket: str):
    """Attribute the enclosed wall time to ``bucket``. Nesting is
    exclusive: a nested region's full duration (its own time plus its
    children's) is subtracted from the parent frame, so concurrent-with
    -nothing code attributes each second to exactly one bucket."""
    if not enabled():
        yield
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    frame = [bucket, time.perf_counter(), 0.0]  # bucket, t0, child_s
    stack.append(frame)
    try:
        yield
    finally:
        stack.pop()
        dt = time.perf_counter() - frame[1]
        own = max(0.0, dt - frame[2])
        with _lock:
            _add_locked(bucket, own)
        if stack:
            stack[-1][2] += dt


def snapshot() -> Dict[str, Any]:
    """Current ledger state. ``buckets`` carries every attribution
    bucket plus derived ``idle`` (wall minus accounted), so the values
    always sum to ``wall_s`` (modulo concurrent-thread overlap)."""
    with _lock:
        wall = 0.0 if _anchor is None else time.perf_counter() - _anchor
        buckets = {b: _buckets.get(b, 0.0) for b in BUCKETS}
        accounted = sum(buckets.values())
        buckets["idle"] = max(0.0, wall - accounted)
        snap: Dict[str, Any] = {
            "job": _job,
            "wall_s": wall,
            "started": _anchor_ts,
            "buckets": buckets,
            "counters": dict(_counters),
        }
        if _mfu is not None:
            snap["mfu"] = _mfu
        return snap


def flush_payload(node: str = "") -> Optional[Dict[str, Any]]:
    """Build the per-process KV payload for the observability flush, or
    ``None`` when this process has nothing to report (keeps idle
    utility processes out of the ``goodput`` namespace). Also mirrors
    the derived gauges onto the shared metrics registry."""
    if not enabled():
        return None
    snap = snapshot()
    if not snap["job"] and not _counters and not any(
            v > 0.0 for b, v in snap["buckets"].items() if b != "idle"):
        return None
    import os

    snap["pid"] = os.getpid()
    snap["time"] = time.time()
    snap["node"] = node
    try:
        obs = _obs()
        wall = snap["wall_s"]
        if wall > 0:
            obs["fraction"].set(snap["buckets"]["step_compute"] / wall)
        if snap.get("mfu") is not None:
            obs["mfu"].set(snap["mfu"])
        counters = snap["counters"]
        obs["compiles"].set(counters.get("compiles", 0))
        obs["recompiles"].set(counters.get("recompiles", 0))
        for b, v in snap["buckets"].items():
            obs["bucket_seconds"].set(v, tags={"bucket": b})
    except Exception:
        pass  # instrument mirroring must never block the flush
    return snap


class CompileWatch:
    """Shape/dtype-keyed jit compile detector.

    ``observe(fn, key)`` returns ``"compile"`` for the first key a
    program ever sees, ``"recompile"`` for a *new* key on an
    already-seen program (same fn, new shapes/dtypes — the storm
    signal), and ``None`` for a warm cache hit."""

    def __init__(self):
        self._seen: Dict[str, set] = {}
        self._lock = threading.Lock()

    def observe(self, fn: str, key: Tuple) -> Optional[str]:
        with self._lock:
            seen = self._seen.setdefault(fn, set())
            if key in seen:
                return None
            seen.add(key)
            return "compile" if len(seen) == 1 else "recompile"


def batch_key(batch: Dict[str, Any]) -> Tuple:
    """A jit-cache-shaped key for a train batch: sorted (name, shape,
    dtype) triples. Deliberately ignores values and the param tree —
    cheap enough for the hot path, and shape/dtype changes are what
    trigger retraces."""
    out = []
    for k in sorted(batch):
        v = batch[k]
        shape = tuple(getattr(v, "shape", ()))
        dtype = str(getattr(v, "dtype", type(v).__name__))
        out.append((k, shape, dtype))
    return tuple(out)


def reset() -> None:
    """Zero the ledger (tests; also the fork path below)."""
    global _job, _anchor, _anchor_ts, _mfu
    with _lock:
        _job = ""
        _anchor = None
        _anchor_ts = 0.0
        _mfu = None
        _buckets.clear()
        _counters.clear()
    _tls.stack = []


def reset_after_fork() -> None:
    """Drop ledger state inherited through a zygote fork: a child that
    keeps the parent image's accumulators re-reports the zygote's
    seconds under a fresh proc key, double-counting them per job (the
    ``_obs_proc_tag`` class of fork bug)."""
    reset()

"""Application metrics API (reference: python/ray/util/metrics.py).

Counter/Gauge/Histogram recorded in-process and AUTO-published: every
worker/driver flushes its registry to the GCS KV (``metrics`` namespace)
every ``metrics_flush_interval_s`` via the core worker's observability loop
(``core_worker._obs_flush_loop``), and every raylet does the same for its
node gauges (``raylet._metrics_loop``) — no manual ``publish_metrics()``
call needed. The dashboard's ``/metrics`` endpoint aggregates all process
snapshots into one Prometheus text exposition (histograms included, with
cumulative ``_bucket``/``_count``/``_sum`` series).

Built-in always-on instruments (reference: metric_defs.cc): task E2E and
execution latency histograms tagged by function, raylet lease-queue depth,
object-store bytes + spill counts, and per-loop event-loop lag gauges.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

_registry: Dict[str, "_Metric"] = {}
_lock = threading.Lock()


class _Metric:
    kind = "metric"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        with _lock:
            _registry[name] = self

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> str:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return json.dumps(merged, sort_keys=True)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: Dict[str, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with _lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def snapshot(self):
        return dict(self._values)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: Dict[str, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with _lock:
            self._values[self._key(tags)] = float(value)

    def snapshot(self):
        return dict(self._values)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = boundaries or [0.01, 0.1, 1, 10, 100]
        self._counts: Dict[str, List[int]] = {}
        self._sums: Dict[str, float] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with _lock:
            counts = self._counts.setdefault(k, [0] * (len(self.boundaries) + 1))
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value

    def snapshot(self):
        return {"counts": {k: list(v) for k, v in self._counts.items()},
                "sums": dict(self._sums),
                "boundaries": list(self.boundaries)}


def reset_after_fork():
    """Zero every instrument's recorded values (instruments stay
    registered — module-level holders keep their references). A
    zygote-forked worker inherits the parent image's registry; without
    this reset the child's first auto-publish re-reports the zygote's
    accumulated counts under a fresh proc key, double-counting them in
    ``/metrics``."""
    with _lock:
        for m in _registry.values():
            for attr in ("_values", "_counts", "_sums"):
                d = getattr(m, attr, None)
                if isinstance(d, dict):
                    d.clear()


def scrape_metrics() -> Dict[str, dict]:
    """All metrics registered in this process."""
    with _lock:
        return {
            name: {"kind": m.kind, "description": m.description,
                   "data": m.snapshot()}
            for name, m in _registry.items()
        }


def publish_metrics():
    """Push this process's metrics to the GCS KV (metrics namespace) NOW.

    Normally unnecessary: the runtime auto-publishes every
    ``metrics_flush_interval_s``. Kept for forcing an immediate flush
    (e.g. right before reading ``/metrics`` in a test)."""
    import os
    from ray_tpu._private import wire

    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.core_worker import _obs_proc_tag

    core = worker_mod.global_worker()
    payload = {"pid": os.getpid(), "time": time.time(),
               "node": getattr(core, "node_hex", ""),
               "metrics": scrape_metrics()}
    # same key as the auto-flusher so the dashboard never double-counts
    core._run(core._gcs_call("KVPut", {
        "ns": "metrics", "key": f"proc_{_obs_proc_tag()}",
        "value": wire.dumps(payload)}))

"""TPU slice gang scheduling.

Reference: python/ray/util/tpu.py (``SlicePlacementGroup`` :52,
``slice_placement_group`` :227) and ``reserve_tpu_slice``
(_private/accelerators/tpu.py:213): two-step reserve — pick an ICI-connected
slice by its slice-name label, then create a STRICT_SPREAD placement group
whose bundles are pinned to that slice's hosts, so a training job's workers
land on one slice and all collective traffic rides ICI.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.common import LABEL_TPU_POD_TYPE, LABEL_TPU_SLICE
from ray_tpu.exceptions import PlacementGroupError
from ray_tpu.util.placement_group import PlacementGroup, placement_group


def available_slices() -> Dict[str, List[dict]]:
    """Alive nodes grouped by slice name label."""
    import ray_tpu

    slices: Dict[str, List[dict]] = {}
    for node in ray_tpu.nodes():
        if not node["alive"]:
            continue
        name = node["labels"].get(LABEL_TPU_SLICE)
        if name:
            slices.setdefault(name, []).append(node)
    return slices


def reserve_tpu_slice(num_hosts: int, pod_type: Optional[str] = None) -> Optional[str]:
    """Pick a slice with >= num_hosts TPU hosts (and matching pod type).

    Reference: reserve_tpu_slice (_private/accelerators/tpu.py:213) — probes
    hosts for their slice name and returns one suitable for gang scheduling.
    """
    for name, nodes in sorted(available_slices().items()):
        if len(nodes) < num_hosts:
            continue
        if pod_type and any(
            n["labels"].get(LABEL_TPU_POD_TYPE) not in (pod_type, None) for n in nodes
        ):
            continue
        return name
    return None


class SlicePlacementGroup:
    """A placement group spanning every host of one reserved TPU slice."""

    def __init__(self, pg: PlacementGroup, slice_name: str, num_hosts: int,
                 chips_per_host: int):
        self.placement_group = pg
        self.slice_name = slice_name
        self.num_hosts = num_hosts
        self.chips_per_host = chips_per_host

    @property
    def num_chips(self) -> int:
        return self.num_hosts * self.chips_per_host

    def ready(self, timeout: float = 300.0) -> bool:
        return self.placement_group.ready(timeout)


def slice_placement_group(
    num_hosts: int,
    chips_per_host: Optional[int] = None,
    pod_type: Optional[str] = None,
    extra_bundle_resources: Optional[Dict[str, float]] = None,
) -> SlicePlacementGroup:
    """Reserve a slice and gang-schedule one bundle per host on it.

    Reference: slice_placement_group (util/tpu.py:227) — bundle label selector
    on the slice-name key so the whole group lands on ICI-connected hosts.
    """
    slice_name = reserve_tpu_slice(num_hosts, pod_type)
    if slice_name is None:
        raise PlacementGroupError(
            f"no TPU slice with {num_hosts} hosts available"
            + (f" (pod_type={pod_type})" if pod_type else ""))
    nodes = available_slices()[slice_name]
    if chips_per_host is None:
        chips_per_host = int(min(n["total_resources"].get("TPU", 0) for n in nodes) or 1)
    bundle = {"TPU": float(chips_per_host), "CPU": 1.0}
    if extra_bundle_resources:
        bundle.update(extra_bundle_resources)
    pg = placement_group(
        bundles=[dict(bundle) for _ in range(num_hosts)],
        strategy="STRICT_SPREAD",
        bundle_label_selector=[{LABEL_TPU_SLICE: slice_name}] * num_hosts,
        name=f"tpu-slice-{slice_name}",
    )
    return SlicePlacementGroup(pg, slice_name, num_hosts, chips_per_host)

"""Multi-agent RL: env abstraction, env-runner actors, and a multi-policy
PPO trainer.

Reference: rllib/env/multi_agent_env.py (dict-keyed obs/reward/termination
per agent), rllib/env/multi_agent_env_runner.py (one runner steps one
multi-agent env, routing each agent's obs through its policy via a
policy_mapping_fn and collecting per-POLICY batches), and the
multi-module learner (core/rl_module/multi_rl_module.py) — realized here
as one jax ActorCritic + PPO update per policy id.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


class MultiAgentEnv:
    """Dict-keyed multi-agent episode protocol (reference:
    rllib/env/multi_agent_env.py). Subclasses define ``agents`` plus
    per-agent observation/action dims and implement reset/step over
    ``{agent_id: value}`` dicts; "__all__" in terminateds ends the episode.
    """

    agents: List[str] = []

    def reset(self, seed: Optional[int] = None) -> Tuple[Dict, Dict]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]
             ) -> Tuple[Dict, Dict, Dict, Dict, Dict]:
        """-> (obs, rewards, terminateds, truncateds, infos), dict-keyed;
        terminateds/truncateds carry the "__all__" aggregate key."""
        raise NotImplementedError

    def observation_dim(self, agent: str) -> int:
        raise NotImplementedError

    def action_count(self, agent: str) -> int:
        raise NotImplementedError


class RendezvousEnv(MultiAgentEnv):
    """Tiny cooperative test env: two agents on a line of L cells move
    left/stay/right; both receive reward 1.0 each step they share a cell.
    Optimal behavior is to meet and stay — mean per-episode return near
    the horizon; random play scores far below."""

    agents = ["a0", "a1"]

    def __init__(self, length: int = 5, horizon: int = 16,
                 seed: int = 0):
        self.length = length
        self.horizon = horizon
        self._rng = np.random.RandomState(seed)
        self._pos: Dict[str, int] = {}
        self._t = 0

    def _obs(self) -> Dict[str, np.ndarray]:
        # each agent sees [own_pos, other_pos] scaled to [0, 1]
        p0, p1 = self._pos["a0"], self._pos["a1"]
        s = float(self.length - 1)
        return {"a0": np.array([p0 / s, p1 / s], np.float32),
                "a1": np.array([p1 / s, p0 / s], np.float32)}

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._pos = {"a0": int(self._rng.randint(self.length)),
                     "a1": int(self._rng.randint(self.length))}
        self._t = 0
        return self._obs(), {}

    def step(self, actions):
        for aid, act in actions.items():
            delta = int(act) - 1  # 0/1/2 -> left/stay/right
            self._pos[aid] = int(np.clip(self._pos[aid] + delta, 0,
                                         self.length - 1))
        self._t += 1
        together = float(self._pos["a0"] == self._pos["a1"])
        rewards = {"a0": together, "a1": together}
        done = self._t >= self.horizon
        terms = {"a0": done, "a1": done, "__all__": done}
        truncs = {"a0": False, "a1": False, "__all__": False}
        return self._obs(), rewards, terms, truncs, {}

    def observation_dim(self, agent):
        return 2

    def action_count(self, agent):
        return 3


_ENV_REGISTRY: Dict[str, Callable[..., MultiAgentEnv]] = {
    "rendezvous": RendezvousEnv,
}


def register_multi_agent_env(name: str, ctor: Callable[..., MultiAgentEnv]):
    _ENV_REGISTRY[name] = ctor


@dataclass
class MultiAgentPPOConfig(AlgorithmConfig):
    env: str = "rendezvous"
    env_config: Dict[str, Any] = field(default_factory=dict)
    # agent_id -> policy_id; None = one shared policy for all agents
    policy_mapping: Optional[Dict[str, str]] = None
    num_env_runners: int = 2
    rollout_length: int = 128
    gamma: float = 0.95
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 3e-3
    entropy_coef: float = 0.01
    vf_coef: float = 0.5
    epochs: int = 4
    hidden: tuple = (32, 32)

    @property
    def algo_cls(self):
        return MultiAgentPPO

    def policy_of(self, agent: str) -> str:
        if self.policy_mapping is None:
            return "shared"
        return self.policy_mapping.get(agent, "shared")


@ray_tpu.remote(num_cpus=0.5)
class _MultiAgentRunner:
    """Steps one multi-agent env, routing each agent through its policy
    and emitting per-POLICY flat batches with GAE-ready fields
    (reference: multi_agent_env_runner.py)."""

    def __init__(self, config_blob: bytes, worker_index: int):
        from ray_tpu._private.serialization import loads_trusted

        # the blob is authored by the driving Algorithm (trusted producer)
        self.cfg: MultiAgentPPOConfig = loads_trusted(config_blob)
        ctor = _ENV_REGISTRY[self.cfg.env]
        self.env = ctor(seed=self.cfg.seed + worker_index * 1000,
                        **self.cfg.env_config)
        self.obs, _ = self.env.reset(seed=self.cfg.seed + worker_index)
        self._apply: Dict[str, Any] = {}
        self._rng_seed = self.cfg.seed * 104729 + worker_index
        self._ep_return = 0.0
        self._done_returns: List[float] = []

    def _policy_apply(self, policy: str, n_act: int):
        if policy not in self._apply:
            from ray_tpu.models.actor_critic import ActorCritic
            from ray_tpu.utils import import_jax

            jax = import_jax()
            model = ActorCritic(n_act, self.cfg.hidden)
            self._apply[policy] = jax.jit(
                lambda params, obs: model.apply({"params": params}, obs))
        return self._apply[policy]

    def sample(self, params_by_policy) -> Dict[str, Dict[str, np.ndarray]]:
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp

        cfg = self.cfg
        key = jax.random.PRNGKey(self._rng_seed)
        self._rng_seed += 1
        # per-AGENT trajectory streams: GAE is a time recursion over one
        # agent's experience — interleaving agents would chain one agent's
        # delta into another's advantage
        cols: Dict[str, Dict[str, list]] = {
            a: {k: [] for k in ("obs", "actions", "logp", "rewards",
                                "dones", "values")}
            for a in self.env.agents}
        for _ in range(cfg.rollout_length):
            actions: Dict[str, int] = {}
            for aid in self.env.agents:
                pol = cfg.policy_of(aid)
                apply = self._policy_apply(pol, self.env.action_count(aid))
                logits, value = apply(
                    params_by_policy[pol],
                    jnp.asarray(self.obs[aid], jnp.float32)[None])
                key, sub = jax.random.split(key)
                act = int(jax.random.categorical(sub, logits[0]))
                logp = float(jax.nn.log_softmax(logits[0])[act])
                actions[aid] = act
                c = cols[aid]
                c["obs"].append(np.asarray(self.obs[aid], np.float32))
                c["actions"].append(act)
                c["logp"].append(logp)
                c["values"].append(float(value[0]))
            next_obs, rewards, terms, truncs, _ = self.env.step(actions)
            done = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            self._ep_return += float(np.mean(list(rewards.values())))
            for aid in self.env.agents:
                cols[aid]["rewards"].append(float(rewards.get(aid, 0.0)))
                cols[aid]["dones"].append(float(done))
            if done:
                self._done_returns.append(self._ep_return)
                self._ep_return = 0.0
                next_obs, _ = self.env.reset()
            self.obs = next_obs
        # per-agent GAE (tail bootstraps with V(next_obs): a rollout cut
        # is truncation, not termination), then concatenate each policy's
        # agent streams into one flat batch
        by_policy: Dict[str, list] = {}
        for aid in self.env.agents:
            pol = cfg.policy_of(aid)
            c = cols[aid]
            batch = {k: np.asarray(v, np.float32) for k, v in c.items()}
            batch["actions"] = batch["actions"].astype(np.int32)
            apply = self._policy_apply(pol, self.env.action_count(aid))
            _, tail_v = apply(params_by_policy[pol],
                              jnp.asarray(self.obs[aid], jnp.float32)[None])
            batch["adv"], batch["returns"] = self._gae(
                batch["values"], batch["rewards"], batch["dones"],
                tail_value=float(tail_v[0]))
            by_policy.setdefault(pol, []).append(batch)
        out: Dict[str, Any] = {}
        for pol, batches in by_policy.items():
            out[pol] = {k: np.concatenate([b[k] for b in batches])
                        for k in batches[0]}
        out["__episode_returns__"] = np.asarray(self._done_returns,
                                                np.float32)
        self._done_returns = []
        return out

    def _gae(self, values, rewards, dones, tail_value: float = 0.0):
        cfg = self.cfg
        T = len(rewards)
        adv = np.zeros(T, np.float32)
        lastgae = 0.0
        next_value = tail_value  # rollout cut = truncation: bootstrap
        for t in reversed(range(T)):
            nonterm = 1.0 - dones[t]
            delta = rewards[t] + cfg.gamma * next_value * nonterm - values[t]
            lastgae = delta + cfg.gamma * cfg.gae_lambda * nonterm * lastgae
            adv[t] = lastgae
            next_value = values[t]
        return adv, adv + values


class MultiAgentPPO(Algorithm):
    """One ActorCritic + optimizer per policy id; each training step
    gathers per-policy batches from every runner and applies the PPO
    clipped update policy-by-policy."""

    def __init__(self, cfg: MultiAgentPPOConfig):
        import cloudpickle

        super().__init__(cfg)
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        from ray_tpu.models.actor_critic import ActorCritic
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp
        import optax

        self._jax = jax
        probe = _ENV_REGISTRY[cfg.env](seed=cfg.seed, **cfg.env_config)
        self.policies = sorted(set(cfg.policy_of(a) for a in probe.agents))
        pol_agents = {p: [a for a in probe.agents if cfg.policy_of(a) == p]
                      for p in self.policies}
        self.params: Dict[str, Any] = {}
        self.opt_states: Dict[str, Any] = {}
        self._models: Dict[str, Any] = {}
        self.opt = optax.chain(optax.clip_by_global_norm(0.5),
                               optax.adam(cfg.lr))
        self._updates: Dict[str, Any] = {}
        for i, pol in enumerate(self.policies):
            a0 = pol_agents[pol][0]
            model = ActorCritic(probe.action_count(a0), cfg.hidden)
            key = jax.random.PRNGKey(cfg.seed + i)
            params = model.init(
                key, jnp.zeros((1, probe.observation_dim(a0))))["params"]
            self._models[pol] = model
            self.params[pol] = params
            self.opt_states[pol] = self.opt.init(params)
            self._updates[pol] = self._build_update(model)

        blob = cloudpickle.dumps(cfg)
        self.runners = [_MultiAgentRunner.remote(blob, i)
                        for i in range(cfg.num_env_runners)]
        self.env_steps = 0
        self._return_window: List[float] = []

    def _build_update(self, model):
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp
        import optax

        cfg = self.config

        def loss_fn(params, batch):
            logits, values = model.apply({"params": params}, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=-1)[:, 0]
            adv = (batch["adv"] - batch["adv"].mean()) / (
                batch["adv"].std() + 1e-8)
            ratio = jnp.exp(logp - batch["logp"])
            pg1 = ratio * adv
            pg2 = jnp.clip(ratio, 1 - cfg.clip_eps, 1 + cfg.clip_eps) * adv
            pg_loss = -jnp.minimum(pg1, pg2).mean()
            vf_loss = ((values - batch["returns"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            return (pg_loss + cfg.vf_coef * vf_loss
                    - cfg.entropy_coef * entropy), (pg_loss, vf_loss, entropy)

        def update(params, opt_state, batch):
            def epoch(carry, _):
                params, opt_state = carry
                (loss, aux), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, batch)
                updates, opt_state = self.opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                epoch, (params, opt_state), None, length=cfg.epochs)
            return params, opt_state, losses[-1]

        return jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        params_np = {p: self._jax.tree.map(np.asarray, v)
                     for p, v in self.params.items()}
        rollouts = ray_tpu.get(
            [r.sample.remote(params_np) for r in self.runners], timeout=600)
        losses = {}
        for pol in self.policies:
            batch = {k: np.concatenate([r[pol][k] for r in rollouts])
                     for k in rollouts[0][pol]}
            self.env_steps += len(batch["actions"])
            jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
            self.params[pol], self.opt_states[pol], loss = self._updates[pol](
                self.params[pol], self.opt_states[pol], jbatch)
            losses[f"loss_{pol}"] = float(loss)
        for r in rollouts:
            self._return_window.extend(r["__episode_returns__"].tolist())
        self._return_window = self._return_window[-100:]
        return {
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else 0.0),
            "num_env_steps_sampled": self.env_steps,
            **losses,
        }

    def get_state(self):
        return {"params": {p: self._jax.tree.map(np.asarray, v)
                           for p, v in self.params.items()},
                "opt_states": {p: self._jax.tree.map(np.asarray, v)
                               for p, v in self.opt_states.items()},
                "env_steps": self.env_steps}

    def set_state(self, state):
        self.params = state["params"]
        if "opt_states" in state:
            self.opt_states = state["opt_states"]
        self.env_steps = state["env_steps"]

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

"""Multi-learner gradient sync over the collective substrate.

Reference: rllib/core/learner/learner_group.py:101 — a LearnerGroup spawns
``num_learners`` remote Learner actors, fans each training batch out to
them, and the learners average gradients before applying updates so their
parameters stay identical (rllib/core/learner/torch/torch_learner.py:524-547
does this with torch DDP). Re-based on this framework's own collective
layer: the CpuStoreGroup tier in CI, XlaGroup over ICI on device — the
last BASELINE.json north-star capability ("multi-learner group uses the
XLA collective backend for gradient sync").

The sync contract every learner core follows: compute gradients on its
shard as *global-denominator contributions* (weighted sums divided by the
global sample count), allreduce-SUM one flat vector of
``[raveled grads | metric scalars]``, unravel, apply. With identical
parameter initialization (same seed on every rank) and identical reduced
gradients, parameters never diverge across learners.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu


def sync_gradients(grads, scalars: np.ndarray, group_name: str,
                   compression=None, error_feedback=None):
    """Allreduce-SUM a gradient pytree and a metrics vector. Returns
    (reduced_grads, reduced_scalars).

    The caller is responsible for scaling: local grads must already be
    global-denominator contributions (sum over ranks == the global-batch
    gradient), and scalars likewise — the sum across ranks IS the value.

    With ``compression`` (int8/fp8/bf16, collective/quant.py) the grad
    vector rides the quantized allreduce (~4x fewer wire bytes for int8:
    quantized contribute leg, fp32 accumulation at the reduce point, one
    re-quantized broadcast leg) while the METRICS vector stays on a plain
    fp32 allreduce — normalization statistics and loss scalars are
    few-float control values, exactly the "when NOT to quantize" case
    (QUANT.md). Pass a persistent ``quant.ErrorFeedback`` so quantization
    error carries into the next step instead of accumulating as bias.
    """
    from ray_tpu import collective as col
    from ray_tpu.utils import import_jax

    import_jax()
    from jax.flatten_util import ravel_pytree

    from ray_tpu.collective import quant

    flat, unravel = ravel_pytree(grads)
    flat = np.asarray(flat, np.float32)
    # resolve BEFORE branching: "none"/"off"/"fp32" spellings mean off
    codec = quant.resolve_codec(compression)
    if codec is None:
        vec = np.concatenate([flat, np.asarray(scalars, np.float32)])
        out = np.asarray(col.allreduce(vec, group_name=group_name))
        return unravel(out[: flat.size]), out[flat.size:]
    if error_feedback is not None:
        qt = error_feedback.encode("sync_gradients", flat)
    else:
        qt = quant.quantize(flat, codec)
    # the metrics vector rides the SAME exchange as a raw fp32 "extra"
    # (summed exactly at the reduce point): one collective round trip,
    # and the few-float leg is never quantized
    out_wire = col.allreduce_quantized(
        quant.to_wire(qt, extra=np.asarray(scalars, np.float32)), codec,
        group_name=group_name)
    reduced = quant.dequantize(quant.from_wire(out_wire)).astype(np.float32)
    out_scalars = np.asarray(out_wire["extra"], np.float32)
    return unravel(reduced), out_scalars


class _LearnerWorker:
    """One learner actor: rank ``rank`` of the gradient-sync group.

    ``factory(rank, world_size, group_name)`` builds the algorithm-specific
    learner core (e.g. PPOLearner), which must expose ``update(batch)``,
    ``get_params()``, ``get_state()``, ``set_state(state)``.
    """

    def __init__(self, factory_blob: bytes, rank: int, world_size: int,
                 group_name: str, backend: str):
        from ray_tpu import collective as col
        from ray_tpu._private.serialization import loads_trusted

        if world_size > 1:
            col.init_collective_group(world_size, rank, backend=backend,
                                      group_name=group_name)
        # store name -> last published version (delta bases)
        self._published: Dict[str, int] = {}
        factory: Callable = loads_trusted(factory_blob)
        self.core = factory(rank=rank, world_size=world_size,
                            group_name=group_name if world_size > 1 else None)
        self.rank = rank

    def ready(self) -> int:
        return self.rank

    def update(self, batch) -> Dict[str, float]:
        return self.core.update(batch)

    def get_params(self):
        from ray_tpu.utils import import_jax

        jax = import_jax()
        return jax.tree.map(np.asarray, self.core.get_params())

    def publish_weights(self, store_name: str, version=None,
                        durable: bool = False, delta: bool = False,
                        compression=None) -> int:
        """Publish current params to the named WeightStore from INSIDE the
        learner — the driver never relays weight bytes. Env-runners pull
        via weights.WeightSync (see env_runner.py).

        ``delta=True`` publishes against this learner's PREVIOUS publish
        to the same store (only changed leaves cross the wire — the first
        publish, and any whose base was retired, go full). ``compression``
        quantizes the chunk payloads (collective/quant.py codecs)."""
        from ray_tpu.utils import import_jax
        from ray_tpu.weights import WeightStore

        jax = import_jax()
        params = jax.tree.map(np.asarray, self.core.get_params())
        delta_from = self._published.get(store_name) if delta else None
        ver = WeightStore(store_name).publish(
            params, version=version, durable=durable,
            delta_from=delta_from, compression=compression)
        self._published[store_name] = ver
        return ver

    def get_state(self):
        return self.core.get_state()

    def set_state(self, state):
        self.core.set_state(state)

    def call(self, method: str, *args, **kwargs):
        """Escape hatch for algorithm-specific learner methods."""
        return getattr(self.core, method)(*args, **kwargs)


class LearnerGroup:
    """Driver-side handle on N learner actors with synced gradients
    (reference: rllib/core/learner/learner_group.py:101).

    ``update(batch)`` ships the batch once through the object store (every
    learner receives the same ref; each slices its own shard per the sync
    contract) and returns rank 0's metrics — ranks agree on all reduced
    metrics by construction.
    """

    def __init__(self, factory: Callable, num_learners: int,
                 backend: str = "cpu", group_name: Optional[str] = None,
                 num_cpus_per_learner: float = 1.0):
        import cloudpickle
        import uuid

        if num_learners < 1:
            raise ValueError("num_learners must be >= 1")
        self.num_learners = num_learners
        self.group_name = group_name or f"learner_group:{uuid.uuid4().hex[:8]}"
        blob = cloudpickle.dumps(factory)
        worker_cls = ray_tpu.remote(_LearnerWorker)
        self.workers = [
            worker_cls.options(num_cpus=num_cpus_per_learner).remote(
                blob, rank, num_learners, self.group_name, backend)
            for rank in range(num_learners)
        ]
        # rendezvous: every rank must be constructed (and its collective
        # side initialized) before the first update, or rank 0's allreduce
        # would block against missing peers
        ray_tpu.get([w.ready.remote() for w in self.workers], timeout=300)

    def update(self, batch) -> Dict[str, float]:
        ref = ray_tpu.put(batch)
        metrics = ray_tpu.get(
            [w.update.remote(ref) for w in self.workers], timeout=600)
        return metrics[0]

    def update_shards(self, batches: List[Any]) -> Dict[str, float]:
        """One synced update where each learner consumes its OWN batch
        (async algorithms: IMPALA/APPO feed different aggregated rollouts
        to each learner; gradients are still averaged). ``batches`` must
        have exactly num_learners entries — every rank must join the
        allreduce or the group deadlocks."""
        if len(batches) != self.num_learners:
            raise ValueError(
                f"update_shards needs exactly {self.num_learners} batches, "
                f"got {len(batches)}")
        metrics = ray_tpu.get(
            [w.update.remote(b) for w, b in zip(self.workers, batches)],
            timeout=600)
        return metrics[0]

    def get_params(self):
        return ray_tpu.get(self.workers[0].get_params.remote(), timeout=300)

    def get_state(self):
        return ray_tpu.get(self.workers[0].get_state.remote(), timeout=300)

    def set_state(self, state):
        ref = ray_tpu.put(state)
        ray_tpu.get([w.set_state.remote(ref) for w in self.workers],
                    timeout=300)

    def publish_weights(self, store_name: str, version=None,
                        durable: bool = False, delta: bool = False,
                        compression=None) -> int:
        """Broadcast current params through the weight plane: rank 0
        publishes (learner params are replicated by the sync contract) and
        every subscribed env-runner pulls the new version. Returns the
        published version (monotonic per store). ``delta``/``compression``
        route the quantized + delta publish tier (see
        ``_LearnerWorker.publish_weights``)."""
        return ray_tpu.get(
            self.workers[0].publish_weights.remote(store_name, version,
                                                   durable, delta,
                                                   compression),
            timeout=300)

    def foreach_learner(self, method: str, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [w.call.remote(method, *args, **kwargs) for w in self.workers],
            timeout=600)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []

"""PPO: env-runner actors sampling + a jax learner (GAE, clipped objective).

Reference: rllib — EnvRunnerGroup (env/env_runner_group.py:70) of actors
stepping gymnasium envs, Learner/LearnerGroup (core/learner/learner.py:112)
doing the update, Algorithm.train() orchestrating one iteration
(algorithms/ppo/ppo.py:390 training_step). TPU-first deviations: the learner
is jax/optax (jit-compiled update over minibatches via lax control flow);
multi-learner gradient sync is GSPMD/psum inside jit rather than torch DDP
(torch_learner.py:524-547).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


@dataclass
class PPOConfig(AlgorithmConfig):
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_length: int = 128
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 1e-3
    entropy_coef: float = 0.005
    vf_coef: float = 0.5
    epochs: int = 8
    num_minibatches: int = 4
    hidden: tuple = (64, 64)
    # multi-learner gradient sync (reference: learner_group.py:101
    # num_learners); backend "cpu" = CpuStoreGroup CI tier, "xla" = ICI
    num_learners: int = 1
    learner_backend: str = "cpu"
    # wire compression of the gradient allreduce (collective/quant.py):
    # None = fp32 (bit-identical to previous releases), "int8"/"fp8"
    # block-quantized with error feedback, "bf16" plain narrowing.
    # Advantage-normalization stats always stay fp32 (QUANT.md).
    grad_compression: Optional[str] = None

    @property
    def algo_cls(self):
        return PPO


@ray_tpu.remote(num_cpus=1)
class EnvRunner:
    """Vectorized env sampler (reference: env/single_agent_env_runner.py:68)."""

    def __init__(self, config_blob: bytes, worker_index: int):
        from ray_tpu._private.serialization import loads_trusted

        from ray_tpu.rl.env_runner import EpisodeTracker, make_vec_env

        # the blob is authored by the driving Algorithm (trusted producer)
        self.cfg: PPOConfig = loads_trusted(config_blob)
        # same-step autoreset (via make_vec_env): the obs after a done is the
        # next episode's reset obs, so every stored transition is a real one
        self.envs, self.obs = make_vec_env(
            self.cfg.env, self.cfg.num_envs_per_runner,
            self.cfg.seed + worker_index * 1000)
        self._apply = None
        self._rng_seed = self.cfg.seed * 7919 + worker_index
        self.episodes = EpisodeTracker(self.cfg.num_envs_per_runner)

    def _policy(self):
        if self._apply is None:
            from ray_tpu.utils import import_jax

            jax = import_jax()

            from ray_tpu.models.actor_critic import ActorCritic

            n_act = int(self.envs.single_action_space.n)
            model = ActorCritic(n_act, self.cfg.hidden)
            self._apply = jax.jit(
                lambda params, obs: model.apply({"params": params}, obs))
        return self._apply

    def sample(self, params) -> Dict[str, np.ndarray]:
        """Collect rollout_length steps from each vector env."""
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp

        apply = self._policy()
        T, N = self.cfg.rollout_length, self.cfg.num_envs_per_runner
        obs_buf = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T + 1, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        key = jax.random.PRNGKey(self._rng_seed)
        self._rng_seed += 1
        for t in range(T):
            logits, value = apply(params, jnp.asarray(self.obs, jnp.float32))
            key, sub = jax.random.split(key)
            action = jax.random.categorical(sub, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, action[:, None], axis=-1)[:, 0]
            action = np.asarray(action)
            obs_buf[t] = self.obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            self.obs, rew, term, trunc, _ = self.envs.step(action)
            done = np.logical_or(term, trunc)
            rew_buf[t] = rew
            done_buf[t] = done
            self.episodes.step(rew, done)
        _, last_value = apply(params, jnp.asarray(self.obs, jnp.float32))
        val_buf[T] = np.asarray(last_value)
        # GAE (reference: rllib postprocessing/advantages)
        adv = np.zeros((T, N), np.float32)
        lastgae = np.zeros(N, np.float32)
        for t in reversed(range(T)):
            nonterminal = 1.0 - done_buf[t]
            delta = (rew_buf[t] + self.cfg.gamma * val_buf[t + 1] * nonterminal
                     - val_buf[t])
            lastgae = delta + self.cfg.gamma * self.cfg.gae_lambda * nonterminal * lastgae
            adv[t] = lastgae
        returns = adv + val_buf[:T]
        ep_returns = self.episodes.pop()
        flat = lambda a: a.reshape((-1,) + a.shape[2:])  # noqa: E731
        return {
            "obs": flat(obs_buf),
            "actions": flat(act_buf),
            "logp": flat(logp_buf),
            "advantages": flat(adv),
            "returns": flat(returns),
            "episode_returns": np.asarray(ep_returns, np.float32),
        }


class PPOLearner:
    """jit-compiled PPO update.

    Single-process by default; with ``world_size > 1`` it is rank ``rank``
    of a LearnerGroup (learner_group.py): every rank sees the full batch,
    derives the SAME seeded minibatch permutation, computes gradients on
    its 1/world slice of each minibatch as global-denominator
    contributions, and allreduce-SUMs them — so the reduced gradient (and
    the advantage-normalization statistics, synced the same way) exactly
    equal the single-learner computation and parameters never diverge.
    Reference: rllib/core/learner/torch/torch_learner.py:524-547 (DDP
    gradient averaging), re-based on the collective layer."""

    def __init__(self, cfg: PPOConfig, obs_dim: int, n_actions: int,
                 world_size: int = 1, rank: int = 0,
                 group_name: Optional[str] = None):
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.actor_critic import ActorCritic

        self.cfg = cfg
        self.model = ActorCritic(n_actions, cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        self.params = self.model.init(key, jnp.zeros((1, obs_dim)))["params"]
        self.opt = optax.chain(optax.clip_by_global_norm(0.5), optax.adam(cfg.lr))
        self.opt_state = self.opt.init(self.params)
        self._jax = jax
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        # quantized grad sync: per-learner error-feedback residual so the
        # int8/fp8 wire stays unbiased across updates (quant.py)
        self._grad_compression = None
        self._grad_ef = None
        if world_size > 1:
            from ray_tpu.collective import quant

            codec = quant.resolve_codec(getattr(cfg, "grad_compression",
                                                None))
            if codec is not None:
                # fail at learner construction, not the first update:
                # only the CPU store-actor backend implements the
                # explicit quantized exchange (same setup-time guard as
                # TrainWorker.setup_grad_sync)
                backend = getattr(cfg, "learner_backend", "cpu")
                if backend != "cpu":
                    raise ValueError(
                        f"grad_compression requires "
                        f"learner_backend='cpu' (got {backend!r}); the "
                        f"XLA tier quantizes inside compiled programs")
                self._grad_compression = codec
                self._grad_ef = quant.ErrorFeedback(codec)

        def loss_fn(params, batch):
            logits, values = self.model.apply({"params": params}, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg1 = ratio * adv
            pg2 = jnp.clip(ratio, 1 - cfg.clip_eps, 1 + cfg.clip_eps) * adv
            pg_loss = -jnp.minimum(pg1, pg2).mean()
            vf_loss = ((values - batch["returns"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + cfg.vf_coef * vf_loss - cfg.entropy_coef * entropy
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update_minibatch(carry, batch):
            params, opt_state = carry
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), {"loss": loss, **aux}

        self._update_minibatch = jax.jit(update_minibatch)

        # distributed path: same loss with explicit per-sample weights and
        # externally-supplied (globally synced) advantage statistics, split
        # into grad-shard / apply so the allreduce sits between them
        def loss_shard(params, batch, w, adv_mean, adv_std, denom):
            logits, values = self.model.apply({"params": params}, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32),
                axis=-1)[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = (batch["advantages"] - adv_mean) / (adv_std + 1e-8)
            pg1 = ratio * adv
            pg2 = jnp.clip(ratio, 1 - cfg.clip_eps, 1 + cfg.clip_eps) * adv
            pg_loss = -(w * jnp.minimum(pg1, pg2)).sum() / denom
            vf_loss = (w * (values - batch["returns"]) ** 2).sum() / denom
            ent = (w * -(jnp.exp(logp_all) * logp_all).sum(-1)).sum() / denom
            total = pg_loss + cfg.vf_coef * vf_loss - cfg.entropy_coef * ent
            return total, jnp.stack([total, pg_loss, vf_loss, ent])

        def grad_shard(params, batch, w, adv_mean, adv_std, denom):
            (_, scalars), grads = jax.value_and_grad(
                loss_shard, has_aux=True)(params, batch, w, adv_mean,
                                          adv_std, denom)
            return grads, scalars

        self._grad_shard = jax.jit(grad_shard)
        self._adv_stats = jax.jit(
            lambda adv, w: jnp.stack([w.sum(), (w * adv).sum(),
                                      (w * adv * adv).sum()]))

        def apply_grads(params, opt_state, grads):
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._apply_grads = jax.jit(apply_grads)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self.world_size > 1:
            return self._update_distributed(batch)
        import numpy as _np

        cfg = self.cfg
        n = len(batch["obs"])
        idx = _np.arange(n)
        rng = _np.random.default_rng(cfg.seed)
        metrics = {}
        mb = max(1, n // cfg.num_minibatches)
        for _ in range(cfg.epochs):
            rng.shuffle(idx)
            for start in range(0, n, mb):
                sel = idx[start:start + mb]
                minibatch = {k: v[sel] for k, v in batch.items()
                             if k != "episode_returns"}
                (self.params, self.opt_state), metrics = self._update_minibatch(
                    (self.params, self.opt_state), minibatch)
        return {k: float(v) for k, v in metrics.items()}

    def _update_distributed(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Rank's share of one LearnerGroup update (see class docstring).

        Two collectives per minibatch: a 3-float advantage-stats allreduce
        (global weighted mean/var — normalization must NOT use shard-local
        statistics or ranks compute different losses), then the flat
        gradient+metrics allreduce. Minibatches are padded to a multiple of
        world_size with zero-weight repeats so shard shapes stay static
        for jit."""
        import numpy as _np

        from ray_tpu import collective as col
        from ray_tpu.rl.learner_group import sync_gradients

        cfg, W = self.cfg, self.world_size
        keys = [k for k in batch if k != "episode_returns"]
        n = len(batch["obs"])
        idx = _np.arange(n)
        rng = _np.random.default_rng(cfg.seed)
        mb = max(1, n // cfg.num_minibatches)
        mvec = _np.zeros(4, _np.float32)
        for _ in range(cfg.epochs):
            rng.shuffle(idx)
            for start in range(0, n, mb):
                sel = idx[start:start + mb]
                shard = -(-len(sel) // W)
                pad = shard * W - len(sel)
                w = _np.ones(len(sel), _np.float32)
                if pad:
                    sel = _np.concatenate([sel, _np.repeat(sel[-1], pad)])
                    w = _np.concatenate([w, _np.zeros(pad, _np.float32)])
                lo = self.rank * shard
                msel, mw = sel[lo:lo + shard], w[lo:lo + shard]
                mbatch = {k: batch[k][msel] for k in keys}
                stats = _np.asarray(col.allreduce(
                    _np.asarray(self._adv_stats(mbatch["advantages"], mw)),
                    group_name=self.group_name))
                wsum = float(stats[0])
                mean = float(stats[1]) / wsum
                std = max(float(stats[2]) / wsum - mean * mean, 0.0) ** 0.5
                grads, scalars = self._grad_shard(
                    self.params, mbatch, mw, mean, std, wsum)
                grads, mvec = sync_gradients(
                    grads, _np.asarray(scalars), self.group_name,
                    compression=self._grad_compression,
                    error_feedback=self._grad_ef)
                self.params, self.opt_state = self._apply_grads(
                    self.params, self.opt_state, grads)
        return {"loss": float(mvec[0]), "pg_loss": float(mvec[1]),
                "vf_loss": float(mvec[2]), "entropy": float(mvec[3])}

    def get_state(self):
        import jax

        to_np = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
        return {"params": to_np(self.params),
                "opt_state": to_np(self.opt_state)}

    def set_state(self, state):
        self.params = state["params"]
        self.opt_state = state["opt_state"]

    def get_params(self):
        return self.params


class PPO(Algorithm):
    """Algorithm driver (reference: Algorithm.step at algorithm.py:1189)."""

    def __init__(self, cfg: PPOConfig):
        import cloudpickle

        import gymnasium as gym

        super().__init__(cfg)
        self.cfg = cfg
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        probe = gym.make(cfg.env)
        obs_dim = int(np.prod(probe.observation_space.shape))
        n_actions = int(probe.action_space.n)
        probe.close()
        self.learner_group = None
        if cfg.num_learners > 1:
            from ray_tpu.rl.learner_group import LearnerGroup

            def factory(rank, world_size, group_name,
                        _cfg=cfg, _obs=obs_dim, _na=n_actions):
                return PPOLearner(_cfg, _obs, _na, world_size=world_size,
                                  rank=rank, group_name=group_name)

            self.learner_group = LearnerGroup(
                factory, cfg.num_learners, backend=cfg.learner_backend)
            self.learner = None
        else:
            self.learner = PPOLearner(cfg, obs_dim, n_actions)
        blob = cloudpickle.dumps(cfg)
        self.runners = [EnvRunner.remote(blob, i)
                        for i in range(cfg.num_env_runners)]
        self._return_window: List[float] = []

    def training_step(self) -> Dict[str, Any]:
        """One iteration: parallel sampling -> PPO update -> weight sync."""
        t0 = time.time()
        if self.learner_group is not None:
            params_np = self.learner_group.get_params()
        else:
            params_np = self._jax_to_np(self.learner.get_params())
        sample_refs = [r.sample.remote(params_np) for r in self.runners]
        rollouts = ray_tpu.get(sample_refs, timeout=600)
        batch = {
            k: np.concatenate([r[k] for r in rollouts])
            for k in rollouts[0]
        }
        if self.learner_group is not None:
            metrics = self.learner_group.update(batch)
        else:
            metrics = self.learner.update(batch)
        self._return_window.extend(batch["episode_returns"].tolist())
        self._return_window = self._return_window[-100:]
        steps = len(batch["obs"])
        return {
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else 0.0),
            "num_env_steps_sampled": steps,
            "steps_per_sec": steps / max(time.time() - t0, 1e-6),
            **metrics,
        }

    @staticmethod
    def _jax_to_np(tree):
        import jax

        return jax.tree.map(lambda x: np.asarray(x), tree)

    def get_state(self):
        if self.learner_group is not None:
            return self.learner_group.get_state()
        return {"params": self._jax_to_np(self.learner.params),
                "opt_state": self._jax_to_np(self.learner.opt_state)}

    def set_state(self, state):
        if self.learner_group is not None:
            self.learner_group.set_state(state)
            return
        self.learner.params = state["params"]
        self.learner.opt_state = state["opt_state"]

    def stop(self):
        if self.learner_group is not None:
            self.learner_group.shutdown()
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

"""DQN: epsilon-greedy env runners + replay buffer + double-DQN jax learner.

Reference: rllib/algorithms/dqn (training_step samples from env runners into
an episode replay buffer, updates with target-network TD, syncs target every
``target_network_update_freq`` steps). TPU-first: the Q-update (double-DQN
target, huber loss, PER weighting) is one jitted program; replay stays in
host numpy (see replay_buffer.py).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer


@dataclass
class DQNConfig(AlgorithmConfig):
    num_env_runners: int = 2
    num_envs_per_runner: int = 2
    rollout_length: int = 64
    buffer_capacity: int = 50_000
    prioritized_replay: bool = True
    learning_starts: int = 500
    train_batch_size: int = 64
    updates_per_iteration: int = 32
    gamma: float = 0.99
    lr: float = 5e-4
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_steps: int = 5_000
    target_update_freq: int = 500  # env steps between target syncs
    hidden: tuple = (64, 64)
    double_q: bool = True

    @property
    def algo_cls(self):
        return DQN


@ray_tpu.remote(num_cpus=1)
class _DQNRunner:
    """Vector env sampler emitting (s, a, r, s', done) transitions."""

    def __init__(self, config_blob: bytes, worker_index: int):
        from ray_tpu._private.serialization import loads_trusted

        from ray_tpu.rl.env_runner import EpisodeTracker, make_vec_env

        # the blob is authored by the driving Algorithm (trusted producer)
        self.cfg: DQNConfig = loads_trusted(config_blob)
        self.envs, self.obs = make_vec_env(
            self.cfg.env, self.cfg.num_envs_per_runner,
            self.cfg.seed + worker_index * 1000)
        self._rng = np.random.default_rng(self.cfg.seed * 131 + worker_index)
        self._apply = None
        self.episodes = EpisodeTracker(self.cfg.num_envs_per_runner)

    def _q(self):
        if self._apply is None:
            from ray_tpu.utils import import_jax

            jax = import_jax()

            from ray_tpu.models.actor_critic import QNetwork

            n_act = int(self.envs.single_action_space.n)
            model = QNetwork(n_act, self.cfg.hidden)
            self._apply = jax.jit(
                lambda params, obs: model.apply({"params": params}, obs))
        return self._apply

    def sample(self, params, epsilon: float) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        from ray_tpu.rl.env_runner import true_next_obs

        apply = self._q()
        T, N = self.cfg.rollout_length, self.cfg.num_envs_per_runner
        shp = self.obs.shape[1:]
        out = {k: np.zeros((T, N) + (shp if k in ("obs", "next_obs") else ()),
                           np.float32)
               for k in ("obs", "next_obs", "rewards", "dones")}
        out["actions"] = np.zeros((T, N), np.int32)
        for t in range(T):
            q = np.asarray(apply(params, jnp.asarray(self.obs, jnp.float32)))
            action = q.argmax(-1)
            explore = self._rng.random(N) < epsilon
            action = np.where(
                explore, self._rng.integers(0, q.shape[-1], N), action)
            nxt, rew, term, trunc, info = self.envs.step(action)
            done = np.logical_or(term, trunc)
            out["obs"][t] = self.obs
            # TD target state: the terminal obs, not the autoreset obs
            out["next_obs"][t] = true_next_obs(nxt, done, info)
            out["actions"][t] = action
            out["rewards"][t] = rew
            # bootstrap through truncation: only true termination zeroes the
            # next-state value; truncation bootstraps V(final_obs)
            out["dones"][t] = term.astype(np.float32)
            self.obs = nxt
            self.episodes.step(rew, done)
        flat = lambda a: a.reshape((-1,) + a.shape[2:])  # noqa: E731
        res = {k: flat(v) for k, v in out.items()}
        res["episode_returns"] = self.episodes.pop()
        return res


class DQN(Algorithm):
    def __init__(self, cfg: DQNConfig):
        import cloudpickle

        import gymnasium as gym

        super().__init__(cfg)
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.actor_critic import QNetwork

        probe = gym.make(cfg.env)
        obs_dim = int(np.prod(probe.observation_space.shape))
        n_actions = int(probe.action_space.n)
        probe.close()

        self.model = QNetwork(n_actions, cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        self.params = self.model.init(key, jnp.zeros((1, obs_dim)))["params"]
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.params)
        self._jax = jax

        def loss_fn(params, target_params, batch):
            q = self.model.apply({"params": params}, batch["obs"])
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
            q_next_t = self.model.apply({"params": target_params},
                                        batch["next_obs"])
            if cfg.double_q:
                q_next_online = self.model.apply({"params": params},
                                                 batch["next_obs"])
                best = jnp.argmax(q_next_online, axis=-1)
            else:
                best = jnp.argmax(q_next_t, axis=-1)
            q_next = jnp.take_along_axis(q_next_t, best[:, None], axis=-1)[:, 0]
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) \
                * jax.lax.stop_gradient(q_next)
            td = q_sel - target
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td,
                              jnp.abs(td) - 0.5)
            w = batch.get("weights", jnp.ones_like(td))
            return (w * huber).mean(), td

        def update(params, target_params, opt_state, batch):
            (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._update = jax.jit(update)

        buf_cls = PrioritizedReplayBuffer if cfg.prioritized_replay \
            else ReplayBuffer
        self.buffer = buf_cls(cfg.buffer_capacity, seed=cfg.seed)
        blob = cloudpickle.dumps(cfg)
        self.runners = [_DQNRunner.remote(blob, i)
                        for i in range(cfg.num_env_runners)]
        self.env_steps = 0
        self._steps_since_target_sync = 0
        self._return_window: List[float] = []

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.env_steps / max(cfg.epsilon_decay_steps, 1))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        t0 = time.time()
        params_np = self._jax.tree.map(np.asarray, self.params)
        eps = self._epsilon()
        rollouts = ray_tpu.get(
            [r.sample.remote(params_np, eps) for r in self.runners],
            timeout=600)
        for r in rollouts:
            self._return_window.extend(r.pop("episode_returns").tolist())
            n = len(r["obs"])
            self.buffer.add_batch(r)
            self.env_steps += n
            self._steps_since_target_sync += n
        self._return_window = self._return_window[-100:]

        loss_val = 0.0
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size)
                idx = batch.pop("idx", None)
                jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
                self.params, self.opt_state, loss, td = self._update(
                    self.params, self.target_params, self.opt_state, jbatch)
                if idx is not None:
                    self.buffer.update_priorities(idx, np.asarray(td))
                loss_val = float(loss)
            if self._steps_since_target_sync >= cfg.target_update_freq:
                self.target_params = self._jax.tree.map(
                    lambda x: x, self.params)
                self._steps_since_target_sync = 0
        return {
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else 0.0),
            "num_env_steps_sampled": self.env_steps,
            "epsilon": eps,
            "loss": loss_val,
            "buffer_size": len(self.buffer),
            "steps_per_sec": (sum(len(r["obs"]) for r in rollouts)
                              / max(time.time() - t0, 1e-6)),
        }

    def get_state(self):
        to_np = lambda t: self._jax.tree.map(np.asarray, t)  # noqa: E731
        return {"params": to_np(self.params),
                "target": to_np(self.target_params),
                "opt_state": to_np(self.opt_state),
                "buffer": self.buffer.state(),
                "env_steps": self.env_steps,
                "steps_since_target_sync": self._steps_since_target_sync}

    def set_state(self, state):
        self.params = state["params"]
        self.target_params = state["target"]
        self.opt_state = state["opt_state"]
        self.buffer.set_state(state["buffer"])
        self.env_steps = state["env_steps"]
        self._steps_since_target_sync = state["steps_since_target_sync"]

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

"""APPO: asynchronous PPO — IMPALA's async runner/aggregator architecture
with a PPO clipped-surrogate learner over V-trace-corrected advantages and
a target network for stable value targets.

Reference: rllib/algorithms/appo/appo.py:347 (training_step: IMPALA
sampling + surrogate loss + periodic target-network sync + optional KL
term). The learner is one jitted program: V-trace (lax.scan over time)
runs on the ONLINE value function and online/behavior ratios (as in the
rllib learner); the TARGET network's role is the optional KL anchor and
a stable policy snapshot — no host loops. Multi-learner: the core plugs
into LearnerGroup like IMPALA's (each rank's target syncs in lockstep
because update counts advance identically on every rank).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import numpy as np

from ray_tpu.rl.impala import IMPALA, IMPALAConfig, _ImpalaLearnerCore, vtrace_returns


@dataclass
class APPOConfig(IMPALAConfig):
    clip_param: float = 0.2
    # updates between target-network syncs (reference:
    # appo.py target_network_update_freq, counted here in learner updates)
    target_update_freq: int = 4
    use_kl_loss: bool = False
    kl_coeff: float = 0.2

    @property
    def algo_cls(self):
        return APPO


class _AppoLearnerCore(_ImpalaLearnerCore):
    """APPO loss + target network on the IMPALA learner chassis."""

    metric_keys = ("loss", "pg_loss", "vf_loss", "entropy", "mean_ratio")

    def __init__(self, cfg, obs_dim, n_actions, world_size=1, rank=0,
                 group_name=None):
        super().__init__(cfg, obs_dim, n_actions, world_size=world_size,
                         rank=rank, group_name=group_name)
        self.target_params = self.params
        self.updates_done = 0

    def _make_loss(self):
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp

        cfg = self.cfg

        def loss_fn(params, extras, batch):
            (target_params,) = extras
            T, B = batch["actions"].shape
            obs_flat = batch["obs"].reshape((T * B,) + batch["obs"].shape[2:])
            obs_all = jnp.concatenate([obs_flat, batch["last_obs"]], axis=0)
            logits_all, values_all = self.model.apply({"params": params},
                                                      obs_all)
            logits = logits_all[: T * B].reshape(T, B, -1)
            values = values_all[: T * B].reshape(T, B)
            last_value = values_all[T * B:]
            # the target network serves the KL anchor (reference: rllib
            # APPO — V-trace itself runs on the ONLINE value function)
            t_logits_all, _ = self.model.apply(
                {"params": target_params}, obs_all)

            acts = batch["actions"][..., None].astype(jnp.int32)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, acts, axis=-1)[..., 0]
            t_logp_all = jax.nn.log_softmax(
                t_logits_all[: T * B].reshape(T, B, -1))

            ratio = jnp.exp(logp - batch["behavior_logp"])
            vs, pg_adv = vtrace_returns(
                jax.lax.stop_gradient(values),
                jax.lax.stop_gradient(last_value),
                batch["rewards"], batch["dones"],
                jax.lax.stop_gradient(ratio),
                gamma=cfg.gamma, rho_clip=cfg.vtrace_rho_clip,
                c_clip=cfg.vtrace_c_clip)
            adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

            surr1 = ratio * adv
            surr2 = jnp.clip(ratio, 1 - cfg.clip_param,
                             1 + cfg.clip_param) * adv
            pg_loss = -jnp.minimum(surr1, surr2).mean()
            vf_loss = ((values - vs) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + cfg.vf_coef * vf_loss - cfg.entropy_coef * entropy
            if cfg.use_kl_loss:
                kl = (jnp.exp(t_logp_all)
                      * (t_logp_all - logp_all)).sum(-1).mean()
                total = total + cfg.kl_coeff * kl
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy, "mean_ratio": ratio.mean()}

        return loss_fn

    def _extras(self):
        return (self.target_params,)

    def _post_update(self):
        self.updates_done += 1
        if self.updates_done % self.cfg.target_update_freq == 0:
            self.target_params = self.params

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["target_params"] = self._jax.tree.map(np.asarray,
                                                    self.target_params)
        state["updates_done"] = self.updates_done
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self.target_params = state.get("target_params", self.params)
        self.updates_done = state.get("updates_done", 0)


class APPO(IMPALA):
    """Inherits the async pipeline (runners, aggregators, relaunch loop)
    and the multi-learner path; only the learner core differs."""

    learner_core_cls = _AppoLearnerCore

"""APPO: asynchronous PPO — IMPALA's async runner/aggregator architecture
with a PPO clipped-surrogate learner over V-trace-corrected advantages and
a target network for stable value targets.

Reference: rllib/algorithms/appo/appo.py:347 (training_step: IMPALA
sampling + surrogate loss + periodic target-network sync + optional KL
term). The learner is one jitted program: V-trace (lax.scan over time)
runs on the ONLINE value function and online/behavior ratios (as in the
rllib learner); the TARGET network's role is the optional KL anchor and
a stable policy snapshot — no host loops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import numpy as np

from ray_tpu.rl.impala import IMPALA, IMPALAConfig


@dataclass
class APPOConfig(IMPALAConfig):
    clip_param: float = 0.2
    # updates between target-network syncs (reference:
    # appo.py target_network_update_freq, counted here in learner updates)
    target_update_freq: int = 4
    use_kl_loss: bool = False
    kl_coeff: float = 0.2

    @property
    def algo_cls(self):
        return APPO


class APPO(IMPALA):
    """Inherits the async pipeline (runners, aggregators, relaunch loop);
    replaces the learner update with the APPO loss + target network."""

    def __init__(self, cfg: APPOConfig):
        super().__init__(cfg)
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp
        import optax

        self.target_params = self.params
        self._updates_done = 0

        from ray_tpu.rl.impala import vtrace_returns

        def vtrace(values, last_value, rewards, dones, rhos):
            return vtrace_returns(
                values, last_value, rewards, dones, rhos, gamma=cfg.gamma,
                rho_clip=cfg.vtrace_rho_clip, c_clip=cfg.vtrace_c_clip)

        def loss_fn(params, target_params, batch):
            T, B = batch["actions"].shape
            obs_flat = batch["obs"].reshape((T * B,) + batch["obs"].shape[2:])
            obs_all = jnp.concatenate([obs_flat, batch["last_obs"]], axis=0)
            logits_all, values_all = self.model.apply({"params": params},
                                                      obs_all)
            logits = logits_all[: T * B].reshape(T, B, -1)
            values = values_all[: T * B].reshape(T, B)
            last_value = values_all[T * B:]
            # the target network serves the KL anchor (reference: rllib
            # APPO — V-trace itself runs on the ONLINE value function)
            t_logits_all, _ = self.model.apply(
                {"params": target_params}, obs_all)
            t_logits = t_logits_all[: T * B].reshape(T, B, -1)

            acts = batch["actions"][..., None].astype(jnp.int32)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, acts, axis=-1)[..., 0]
            t_logp_all = jax.nn.log_softmax(t_logits)

            ratio = jnp.exp(logp - batch["behavior_logp"])
            vs, pg_adv = vtrace(jax.lax.stop_gradient(values),
                                jax.lax.stop_gradient(last_value),
                                batch["rewards"], batch["dones"],
                                jax.lax.stop_gradient(ratio))
            adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

            surr1 = ratio * adv
            surr2 = jnp.clip(ratio, 1 - cfg.clip_param,
                             1 + cfg.clip_param) * adv
            pg_loss = -jnp.minimum(surr1, surr2).mean()
            vf_loss = ((values - vs) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + cfg.vf_coef * vf_loss - cfg.entropy_coef * entropy
            if cfg.use_kl_loss:
                kl = (jnp.exp(t_logp_all)
                      * (t_logp_all - logp_all)).sum(-1).mean()
                total = total + cfg.kl_coeff * kl
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy, "mean_ratio": ratio.mean()}

        def appo_update(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"loss": loss, **aux}

        self._appo_update = jax.jit(appo_update)

        def update(params, opt_state, batch):
            params, opt_state, metrics = self._appo_update(
                params, self.target_params, opt_state, batch)
            self._updates_done += 1
            if self._updates_done % cfg.target_update_freq == 0:
                self.target_params = params
            return params, opt_state, metrics

        self._update = update  # IMPALA.training_step drives this

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["target_params"] = self._to_np(self.target_params)
        state["updates_done"] = self._updates_done
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self.target_params = state.get("target_params", self.params)
        self._updates_done = state.get("updates_done", 0)

"""Replay buffers (reference: rllib/utils/replay_buffers/).

Uniform transition buffer + proportional prioritized variant (sum-tree).
Buffers are host-side numpy ring buffers — the TPU only sees the sampled
minibatch, which keeps HBM free for the learner and makes sampling O(1)
per item regardless of capacity.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform FIFO transition buffer over named arrays."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._storage: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if not self._storage:
            for k, v in batch.items():
                v = np.asarray(v)
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._storage[k][idx] = v
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._storage.items()}

    def state(self) -> dict:
        return {"storage": {k: v[: self._size].copy()
                            for k, v in self._storage.items()},
                "next": self._next, "size": self._size}

    def set_state(self, state: dict) -> None:
        self._storage = {}
        for k, v in state["storage"].items():
            arr = np.zeros((self.capacity,) + v.shape[1:], v.dtype)
            arr[: len(v)] = v
            self._storage[k] = arr
        self._next = state["next"]
        self._size = state["size"]


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (PER) via a flat sum-tree.

    Reference: rllib/utils/replay_buffers/prioritized_episode_buffer.py.
    ``sample`` also returns ``weights`` (importance corrections) and ``idx``
    for ``update_priorities`` after the TD errors are known.
    """

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4,
                 seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        # binary-heap-layout sum tree: leaves at [capacity, 2*capacity)
        self._tree = np.zeros(2 * self.capacity, np.float64)
        self._max_prio = 1.0

    def _set_prio(self, idx: np.ndarray, prio: np.ndarray) -> None:
        pos = np.asarray(idx) + self.capacity
        self._tree[pos] = prio
        pos = np.unique(pos // 2)
        while pos.size and pos[0] >= 1:
            self._tree[pos] = self._tree[2 * pos] + self._tree[2 * pos + 1]
            pos = np.unique(pos // 2)
            pos = pos[pos >= 1]

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        idx = (self._next + np.arange(n)) % self.capacity
        super().add_batch(batch)
        self._set_prio(idx, np.full(n, self._max_prio ** self.alpha))

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        total = self._tree[1]
        targets = self._rng.uniform(0, total, size=batch_size)
        idx = np.empty(batch_size, np.int64)
        for i, t in enumerate(targets):
            pos = 1
            while pos < self.capacity:
                left = 2 * pos
                if t <= self._tree[left]:
                    pos = left
                else:
                    t -= self._tree[left]
                    pos = left + 1
            idx[i] = pos - self.capacity
        idx = np.minimum(idx, max(self._size - 1, 0))
        probs = self._tree[idx + self.capacity] / max(total, 1e-12)
        weights = (self._size * probs + 1e-12) ** (-self.beta)
        weights /= weights.max() + 1e-12
        out = {k: v[idx] for k, v in self._storage.items()}
        out["weights"] = weights.astype(np.float32)
        out["idx"] = idx
        return out

    def update_priorities(self, idx: np.ndarray, td_errors: np.ndarray) -> None:
        prio = (np.abs(td_errors) + 1e-6)
        self._max_prio = max(self._max_prio, float(prio.max()))
        self._set_prio(np.asarray(idx), prio ** self.alpha)

    def state(self) -> dict:
        d = super().state()
        d["prios"] = self._tree[self.capacity: self.capacity + self._size].copy()
        d["max_prio"] = self._max_prio
        return d

    def set_state(self, state: dict) -> None:
        super().set_state(state)
        self._tree[:] = 0.0
        if state["size"]:
            self._set_prio(np.arange(state["size"]), state["prios"])
        self._max_prio = state["max_prio"]

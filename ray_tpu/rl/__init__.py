"""ray_tpu.rl: reinforcement learning (reference: rllib core loop).

Round 1 ships PPO (env-runner actors + jax learner); the Algorithm/Config
shape mirrors rllib's AlgorithmConfig.build() -> Algorithm.train().
"""

from ray_tpu.rl.ppo import PPO, PPOConfig, PPOLearner

__all__ = ["PPO", "PPOConfig", "PPOLearner"]

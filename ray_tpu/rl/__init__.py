"""ray_tpu.rl: reinforcement learning (reference: rllib core loop).

Algorithms follow rllib's ``AlgorithmConfig.build() -> Algorithm.train()``
shape (algorithms/algorithm.py:212): PPO (sync on-policy), DQN (replay +
target nets, PER, double-Q), IMPALA (async sampling + aggregator actors +
V-trace). All learners are jitted jax programs; env runners are actors.
"""

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.dqn import DQN, DQNConfig
from ray_tpu.rl.appo import APPO, APPOConfig
from ray_tpu.rl.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.multi_agent import (
    MultiAgentEnv,
    MultiAgentPPO,
    MultiAgentPPOConfig,
    RendezvousEnv,
    register_multi_agent_env,
)
from ray_tpu.rl.offline import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rl.ppo import PPO, PPOConfig, PPOLearner
from ray_tpu.rl.sac import SAC, SACConfig
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer

__all__ = [
    "SAC",
    "SACConfig",
    "BC",
    "BCConfig",
    "MARWIL",
    "MARWILConfig",
    "Algorithm", "AlgorithmConfig",
    "PPO", "PPOConfig", "PPOLearner",
    "DQN", "DQNConfig",
    "IMPALA", "IMPALAConfig",
    "APPO", "APPOConfig",
    "MultiAgentEnv", "MultiAgentPPO", "MultiAgentPPOConfig",
    "RendezvousEnv", "register_multi_agent_env",
    "ReplayBuffer", "PrioritizedReplayBuffer",
]

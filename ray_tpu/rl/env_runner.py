"""Shared vector-env plumbing for env-runner actors.

Reference: rllib env/single_agent_env_runner.py:68. One place for the
autoreset semantics so every algorithm gets them right:

- SAME_STEP autoreset (gymnasium >= 1.0): the obs returned at a done step is
  the NEXT episode's reset obs; the true terminal obs is in
  ``infos["final_obs"]``. ``true_next_obs`` recovers it so TD targets
  bootstrap from the state that was actually reached.
- ``term`` vs ``trunc``: only true termination should zero the bootstrap;
  truncation (time limits) should bootstrap from V(final_obs).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def make_vec_env(env_name: str, num_envs: int, seed: int):
    import gymnasium as gym

    fns = [lambda: gym.make(env_name) for _ in range(num_envs)]
    try:
        from gymnasium.vector import AutoresetMode

        envs = gym.vector.SyncVectorEnv(fns,
                                        autoreset_mode=AutoresetMode.SAME_STEP)
    except (ImportError, TypeError):
        envs = gym.vector.SyncVectorEnv(fns)
    obs, _ = envs.reset(seed=seed)
    return envs, obs


def true_next_obs(step_obs: np.ndarray, done: np.ndarray, info: dict
                  ) -> np.ndarray:
    """Next-state observations for TD targets: where an episode just ended,
    substitute the terminal obs recorded in info for the reset obs."""
    finals = info.get("final_obs", info.get("final_observation"))
    if finals is None or not np.any(done):
        return step_obs
    out = np.array(step_obs, copy=True)
    for i in np.nonzero(done)[0]:
        if finals[i] is not None:
            out[i] = finals[i]
    return out


class WeightSync:
    """Env-runner-side weight subscription over the weight plane.

    An env-runner constructs one of these next to its policy and calls
    :meth:`poll` between rollouts: if the learner published a newer version
    to the store, ``apply_fn(tree)`` installs it and the new version number
    is returned (None otherwise). Versions are monotonic — a runner can
    never regress to older weights, and N runners pulling the same version
    fan out over the store's owner-tracked chunk refs (no learner-side
    per-runner serialization).
    """

    def __init__(self, store_name: str, apply_fn=None, start_after: int = -1):
        from ray_tpu.weights import WeightStore

        self._store = WeightStore(store_name)
        self._sub = self._store.subscribe(start_after=start_after)
        self._apply = apply_fn
        self.weights = None
        self.version = start_after

    def poll(self, timeout: float = 0.0) -> Optional[int]:
        """Install the newest published weights if any. ``timeout`` > 0
        long-polls the store (blocking wait for the next publish)."""
        out = self._sub.poll(timeout=timeout)
        if out is None:
            return None
        version, tree = out
        assert version > self.version, (version, self.version)
        self.version = version
        if self._apply is not None:
            self._apply(tree)
        else:
            self.weights = tree
        return version


class EpisodeTracker:
    """Accumulates per-env returns; pops finished-episode returns."""

    def __init__(self, num_envs: int):
        self._acc = np.zeros(num_envs)
        self._finished: List[float] = []

    def step(self, rewards: np.ndarray, done: np.ndarray) -> None:
        self._acc += rewards
        for i in np.nonzero(done)[0]:
            self._finished.append(float(self._acc[i]))
            self._acc[i] = 0.0

    def pop(self) -> np.ndarray:
        out, self._finished = self._finished, []
        return np.asarray(out, np.float32)

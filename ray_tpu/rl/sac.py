"""SAC: off-policy continuous control with entropy regularization.

Reference: rllib/algorithms/sac — twin Q critics with target networks,
tanh-squashed Gaussian actor, automatic entropy-temperature tuning
(sac_torch_learner's alpha loss), env-runner actors feeding a replay
buffer. TPU-first: the whole update (twin critics + actor + alpha +
polyak) is one jit-compiled optax step.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.replay_buffer import ReplayBuffer


@dataclass
class SACConfig(AlgorithmConfig):
    env: str = "Pendulum-v1"
    num_env_runners: int = 1
    num_envs_per_runner: int = 4
    rollout_length: int = 64
    gamma: float = 0.99
    tau: float = 0.01
    lr: float = 3e-4
    alpha_lr: float = 3e-4
    buffer_capacity: int = 100_000
    batch_size: int = 256
    updates_per_iteration: int = 32
    warmup_steps: int = 1000
    hidden: tuple = (128, 128)

    @property
    def algo_cls(self):
        return SAC


@ray_tpu.remote(num_cpus=1)
class _SACRunner:
    """Continuous-action sampler (squashed-Gaussian exploration)."""

    def __init__(self, config_blob: bytes, worker_index: int):
        from ray_tpu._private.serialization import loads_trusted

        from ray_tpu.rl.env_runner import EpisodeTracker, make_vec_env

        # the blob is authored by the driving Algorithm (trusted producer)
        self.cfg: SACConfig = loads_trusted(config_blob)
        self.envs, self.obs = make_vec_env(
            self.cfg.env, self.cfg.num_envs_per_runner,
            self.cfg.seed + worker_index * 1000)
        self._apply = None
        self._rng_seed = self.cfg.seed * 9973 + worker_index
        self.episodes = EpisodeTracker(self.cfg.num_envs_per_runner)
        space = self.envs.single_action_space
        self.act_low = np.asarray(space.low, np.float32)
        self.act_high = np.asarray(space.high, np.float32)

    def _policy(self):
        if self._apply is None:
            from ray_tpu.utils import import_jax

            jax = import_jax()

            from ray_tpu.models.actor_critic import SquashedGaussianActor

            act_dim = int(np.prod(self.envs.single_action_space.shape))
            model = SquashedGaussianActor(act_dim, self.cfg.hidden)
            self._apply = jax.jit(
                lambda params, obs, key: model.apply(
                    {"params": params}, obs, key, method=model.sample))
        return self._apply

    def sample(self, params, random_actions: bool = False) -> Dict[str, np.ndarray]:
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp

        T, N = self.cfg.rollout_length, self.cfg.num_envs_per_runner
        act_shape = self.envs.single_action_space.shape
        obs_buf = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        act_buf = np.zeros((T, N) + act_shape, np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        next_buf = np.zeros_like(obs_buf)
        apply = self._policy()
        key = jax.random.PRNGKey(self._rng_seed)
        self._rng_seed += 1
        scale = (self.act_high - self.act_low) / 2.0
        mid = (self.act_high + self.act_low) / 2.0
        for t in range(T):
            if random_actions:
                action = np.random.default_rng(self._rng_seed * 131 + t).uniform(
                    -1.0, 1.0, (N,) + act_shape).astype(np.float32)
            else:
                key, sub = jax.random.split(key)
                action, _ = apply(params, jnp.asarray(self.obs, jnp.float32), sub)
                action = np.asarray(action)
            env_action = action * scale + mid
            obs_buf[t] = self.obs
            act_buf[t] = action
            self.obs, rew, term, trunc, info = self.envs.step(env_action)
            from ray_tpu.rl.env_runner import true_next_obs

            done = np.logical_or(term, trunc)
            next_buf[t] = true_next_obs(self.obs, done, info)
            rew_buf[t] = rew
            # bootstrap through time-limit truncations (Pendulum always
            # truncates): only true terminations cut the value target
            done_buf[t] = term.astype(np.float32)
            self.episodes.step(rew, done)
        flat = lambda a: a.reshape((-1,) + a.shape[2:])  # noqa: E731
        return {
            "obs": flat(obs_buf), "actions": flat(act_buf),
            "rewards": flat(rew_buf), "dones": flat(done_buf),
            "next_obs": flat(next_buf),
            "episode_returns": np.asarray(self.episodes.pop(), np.float32),
        }


class SACLearner:
    """One jit step: twin-critic Bellman + actor + temperature + polyak."""

    def __init__(self, cfg: SACConfig, obs_dim: int, act_dim: int):
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.actor_critic import ContinuousQ, SquashedGaussianActor

        self.cfg = cfg
        key = jax.random.PRNGKey(cfg.seed)
        k1, k2, k3 = jax.random.split(key, 3)
        self.actor = SquashedGaussianActor(act_dim, cfg.hidden)
        self.q = ContinuousQ(cfg.hidden)
        dummy_obs = jnp.zeros((1, obs_dim))
        dummy_act = jnp.zeros((1, act_dim))
        self.actor_params = self.actor.init(k1, dummy_obs)["params"]
        self.q1_params = self.q.init(k2, dummy_obs, dummy_act)["params"]
        self.q2_params = self.q.init(k3, dummy_obs, dummy_act)["params"]
        self.q1_target = jax.tree.map(lambda x: x, self.q1_params)
        self.q2_target = jax.tree.map(lambda x: x, self.q2_params)
        self.log_alpha = jnp.zeros(())
        self.target_entropy = -float(act_dim)

        self.actor_opt = optax.adam(cfg.lr)
        self.q_opt = optax.adam(cfg.lr)
        self.alpha_opt = optax.adam(cfg.alpha_lr)
        self.actor_opt_state = self.actor_opt.init(self.actor_params)
        self.q1_opt_state = self.q_opt.init(self.q1_params)
        self.q2_opt_state = self.q_opt.init(self.q2_params)
        self.alpha_opt_state = self.alpha_opt.init(self.log_alpha)

        actor_model, q_model = self.actor, self.q

        def sample_action(params, obs, key):
            return actor_model.apply({"params": params}, obs, key,
                                     method=actor_model.sample)

        def step(state, batch, key):
            (actor_params, q1, q2, q1_t, q2_t, log_alpha,
             a_opt, q1_opt, q2_opt, al_opt) = state
            alpha = jnp.exp(log_alpha)
            key, k_next, k_pi = jax.random.split(key, 3)

            # critic targets
            next_act, next_logp = sample_action(actor_params, batch["next_obs"], k_next)
            tq1 = q_model.apply({"params": q1_t}, batch["next_obs"], next_act)
            tq2 = q_model.apply({"params": q2_t}, batch["next_obs"], next_act)
            target_v = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) * target_v
            target = jax.lax.stop_gradient(target)

            def q_loss(params):
                pred = q_model.apply({"params": params}, batch["obs"], batch["actions"])
                return ((pred - target) ** 2).mean()

            q1_l, q1_g = jax.value_and_grad(q_loss)(q1)
            q2_l, q2_g = jax.value_and_grad(q_loss)(q2)
            upd, q1_opt = self.q_opt.update(q1_g, q1_opt, q1)
            q1 = optax.apply_updates(q1, upd)
            upd, q2_opt = self.q_opt.update(q2_g, q2_opt, q2)
            q2 = optax.apply_updates(q2, upd)

            # actor
            def pi_loss(params):
                act, logp = sample_action(params, batch["obs"], k_pi)
                qv = jnp.minimum(
                    q_model.apply({"params": q1}, batch["obs"], act),
                    q_model.apply({"params": q2}, batch["obs"], act))
                return (alpha * logp - qv).mean(), logp

            (pi_l, logp), pi_g = jax.value_and_grad(pi_loss, has_aux=True)(actor_params)
            upd, a_opt = self.actor_opt.update(pi_g, a_opt, actor_params)
            actor_params = optax.apply_updates(actor_params, upd)

            # temperature
            def alpha_loss(la):
                return (-jnp.exp(la) * jax.lax.stop_gradient(
                    logp + self.target_entropy)).mean()

            al_l, al_g = jax.value_and_grad(alpha_loss)(log_alpha)
            upd, al_opt = self.alpha_opt.update(al_g, al_opt, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, upd)

            # polyak
            polyak = lambda t, s: jax.tree.map(  # noqa: E731
                lambda a, b: a * (1 - cfg.tau) + b * cfg.tau, t, s)
            q1_t = polyak(q1_t, q1)
            q2_t = polyak(q2_t, q2)
            new_state = (actor_params, q1, q2, q1_t, q2_t, log_alpha,
                         a_opt, q1_opt, q2_opt, al_opt)
            return new_state, {"q_loss": (q1_l + q2_l) / 2, "pi_loss": pi_l,
                               "alpha": jnp.exp(log_alpha),
                               "entropy": -logp.mean()}

        self._step = jax.jit(step)
        self._jax = jax
        self._key = jax.random.PRNGKey(cfg.seed + 17)

    def state_tuple(self):
        return (self.actor_params, self.q1_params, self.q2_params,
                self.q1_target, self.q2_target, self.log_alpha,
                self.actor_opt_state, self.q1_opt_state, self.q2_opt_state,
                self.alpha_opt_state)

    def load_state_tuple(self, st):
        (self.actor_params, self.q1_params, self.q2_params,
         self.q1_target, self.q2_target, self.log_alpha,
         self.actor_opt_state, self.q1_opt_state, self.q2_opt_state,
         self.alpha_opt_state) = st

    def update(self, batches: List[Dict[str, np.ndarray]]) -> Dict[str, float]:
        jax = self._jax
        st = self.state_tuple()
        metrics = {}
        for batch in batches:
            self._key, sub = jax.random.split(self._key)
            st, metrics = self._step(st, batch, sub)
        self.load_state_tuple(st)
        return {k: float(v) for k, v in metrics.items()}


class SAC(Algorithm):
    def __init__(self, cfg: SACConfig):
        import cloudpickle

        import gymnasium as gym

        super().__init__(cfg)
        self.cfg = cfg
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        probe = gym.make(cfg.env)
        obs_dim = int(np.prod(probe.observation_space.shape))
        act_dim = int(np.prod(probe.action_space.shape))
        probe.close()
        self.learner = SACLearner(cfg, obs_dim, act_dim)
        blob = cloudpickle.dumps(cfg)
        self.runners = [_SACRunner.remote(blob, i)
                        for i in range(cfg.num_env_runners)]
        self.buffer = ReplayBuffer(cfg.buffer_capacity, cfg.seed)
        self._steps_sampled = 0
        self._return_window: List[float] = []

    def training_step(self) -> Dict[str, Any]:
        import jax

        t0 = time.time()
        params_np = jax.tree.map(np.asarray, self.learner.actor_params)
        warmup = self._steps_sampled < self.cfg.warmup_steps
        rollouts = ray_tpu.get(
            [r.sample.remote(params_np, warmup) for r in self.runners],
            timeout=600)
        for roll in rollouts:
            self._return_window.extend(roll.pop("episode_returns").tolist())
            self.buffer.add_batch(roll)
            self._steps_sampled += len(roll["obs"])
        self._return_window = self._return_window[-50:]
        metrics = {}
        if not warmup and len(self.buffer) >= self.cfg.batch_size:
            batches = [self.buffer.sample(self.cfg.batch_size)
                       for _ in range(self.cfg.updates_per_iteration)]
            metrics = self.learner.update(batches)
        return {
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else 0.0),
            "num_env_steps_sampled": self._steps_sampled,
            "steps_per_sec": (self.cfg.rollout_length
                              * self.cfg.num_envs_per_runner
                              * len(self.runners)) / max(time.time() - t0, 1e-6),
            **metrics,
        }

    def get_state(self):
        import jax

        return {"state": jax.tree.map(np.asarray, self.learner.state_tuple())}

    def set_state(self, state):
        self.learner.load_state_tuple(state["state"])

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

"""Offline RL: behavior cloning (BC) and MARWIL.

Reference: rllib/algorithms/bc + rllib/algorithms/marwil — learn a policy
from a fixed dataset of (obs, action[, reward]) transitions with no
environment interaction during training; MARWIL weights the imitation
loss by exponentiated advantages against a learned value baseline
(marwil.py's beta). Datasets ride ray_tpu.data (reference: offline data on
ray.data)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


@dataclass
class BCConfig(AlgorithmConfig):
    env: str = "CartPole-v1"
    # dataset for cfg.build(): dict of arrays or a ray_tpu.data.Dataset
    offline_data: Any = None
    lr: float = 1e-3
    batch_size: int = 256
    updates_per_iteration: int = 32
    hidden: tuple = (64, 64)
    # MARWIL advantage weighting; 0.0 == plain BC (reference: marwil.py beta)
    beta: float = 0.0
    vf_coef: float = 1.0
    gamma: float = 0.99
    eval_episodes: int = 8

    @property
    def algo_cls(self):
        return BC


@dataclass
class MARWILConfig(BCConfig):
    beta: float = 1.0

    @property
    def algo_cls(self):
        return MARWIL


class _OfflineLearner:
    """jit-compiled weighted-imitation update over an offline batch."""

    def __init__(self, cfg: BCConfig, obs_dim: int, n_actions: int):
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.actor_critic import ActorCritic

        self.cfg = cfg
        self.model = ActorCritic(n_actions, cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        self.params = self.model.init(key, jnp.zeros((1, obs_dim)))["params"]
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.params)
        self._jax = jax

        def loss_fn(params, batch):
            logits, values = self.model.apply({"params": params}, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32),
                axis=-1)[:, 0]
            if cfg.beta > 0.0:
                adv = batch["returns"] - values
                # center + scale: below-average transitions (e.g. random
                # filler in a mixed dataset) get exponentially small weight
                # even before the value baseline converges
                norm = (adv - adv.mean()) / (adv.std() + 1e-8)
                weights = jnp.exp(cfg.beta * jax.lax.stop_gradient(norm))
                weights = jnp.clip(weights, 0.0, 20.0)
                pi_loss = -(weights * logp).mean()
                vf_loss = (adv ** 2).mean()
                total = pi_loss + cfg.vf_coef * vf_loss
            else:
                pi_loss = -logp.mean()
                vf_loss = jnp.zeros(())
                total = pi_loss
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss}

        def update(carry, batch):
            params, opt_state = carry
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), {"loss": loss, **aux}

        self._update = jax.jit(update)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        (self.params, self.opt_state), metrics = self._update(
            (self.params, self.opt_state), batch)
        return {k: float(v) for k, v in metrics.items()}


class BC(Algorithm):
    """Behavior cloning from an offline dataset.

    The dataset may be a ``ray_tpu.data.Dataset`` of rows with
    ``obs``/``actions`` (MARWIL additionally needs per-episode ``rewards``
    + ``dones`` or precomputed ``returns``), or a plain dict of arrays via
    ``config.offline_data``."""

    def __init__(self, cfg: BCConfig, offline_data=None):
        import gymnasium as gym

        super().__init__(cfg)
        self.cfg = cfg
        if offline_data is None:
            offline_data = cfg.offline_data
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        probe = gym.make(cfg.env)
        obs_dim = int(np.prod(probe.observation_space.shape))
        n_actions = int(probe.action_space.n)
        probe.close()
        self.learner = _OfflineLearner(cfg, obs_dim, n_actions)
        self._data = self._load_data(offline_data)
        self._rng = np.random.default_rng(cfg.seed)

    def _load_data(self, offline_data) -> Dict[str, np.ndarray]:
        if offline_data is None:
            raise ValueError("BC/MARWIL require offline_data")
        if isinstance(offline_data, dict):
            data = {k: np.asarray(v) for k, v in offline_data.items()}
        else:  # a ray_tpu.data.Dataset of row dicts
            rows = offline_data.take_all()
            keys = rows[0].keys()
            data = {k: np.asarray([r[k] for r in rows]) for k in keys}
        data["obs"] = data["obs"].astype(np.float32)
        data["actions"] = data["actions"].astype(np.int32)
        if self.cfg.beta > 0.0 and "returns" not in data:
            data["returns"] = self._discounted_returns(data)
        if "returns" in data:
            r = data["returns"].astype(np.float32)
            # standardize: the value head shares a torso with the policy, so
            # unscaled-return regression gradients would swamp the
            # imitation signal (advantages only need relative scale)
            data["returns"] = (r - r.mean()) / (r.std() + 1e-8)
        return data

    def _discounted_returns(self, data) -> np.ndarray:
        rewards = data["rewards"].astype(np.float32)
        dones = data["dones"].astype(bool)
        returns = np.zeros_like(rewards)
        acc = 0.0
        for i in reversed(range(len(rewards))):
            acc = rewards[i] + self.cfg.gamma * (0.0 if dones[i] else acc)
            returns[i] = acc
        return returns

    def training_step(self) -> Dict[str, Any]:
        n = len(self._data["obs"])
        metrics = {}
        for _ in range(self.cfg.updates_per_iteration):
            idx = self._rng.integers(0, n, self.cfg.batch_size)
            batch = {k: v[idx] for k, v in self._data.items()
                     if k in ("obs", "actions", "returns")}
            metrics = self.learner.update(batch)
        return metrics

    def evaluate(self) -> Dict[str, float]:
        """Greedy rollouts in the real env (reference: evaluation duration
        on the Algorithm)."""
        import gymnasium as gym
        import jax
        import jax.numpy as jnp

        env = gym.make(self.cfg.env)
        apply = getattr(self, "_eval_apply", None)
        if apply is None:
            apply = self._eval_apply = jax.jit(
                lambda p, o: self.learner.model.apply({"params": p}, o))
        total = []
        for ep in range(self.cfg.eval_episodes):
            obs, _ = env.reset(seed=self.cfg.seed + ep)
            done, ret = False, 0.0
            while not done:
                logits, _ = apply(self.learner.params,
                                  jnp.asarray(obs, jnp.float32)[None])
                action = int(jnp.argmax(logits[0]))
                obs, rew, term, trunc, _ = env.step(action)
                ret += float(rew)
                done = term or trunc
            total.append(ret)
        env.close()
        return {"episode_return_mean": float(np.mean(total))}

    def get_state(self):
        import jax

        return {"params": jax.tree.map(np.asarray, self.learner.params)}

    def set_state(self, state):
        self.learner.params = state["params"]

    def stop(self):
        pass


class MARWIL(BC):
    """Advantage-weighted imitation (beta > 0)."""

"""IMPALA: async env-runners + aggregator actors + V-trace jax learner.

Reference: rllib/algorithms/impala/impala.py:605 (async sampling loop) and
:133-148 (aggregator actors). Runners sample continuously with whatever
params they last received; the learner corrects the resulting policy lag
with V-trace importance weighting (Espeholt et al. 2018), computed inside
one jitted program via ``lax.scan`` over the time axis — no host loop.
Aggregator actors stack several rollouts into one learner batch off the
driver, so the driver only moves object refs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


def vtrace_returns(values, last_value, rewards, dones, rhos, *, gamma,
                   rho_clip, c_clip):
    """V-trace targets + pg advantages over [T, B] inputs (Espeholt et al.
    2018), scanned backwards in time. Shared by the IMPALA and APPO
    learners — one implementation to keep their corrections in sync."""
    from ray_tpu.utils import import_jax

    jax = import_jax()
    import jax.numpy as jnp

    rho_cl = jnp.minimum(rhos, rho_clip)
    c_cl = jnp.minimum(rhos, c_clip)
    nonterm = 1.0 - dones
    values_tp1 = jnp.concatenate([values[1:], last_value[None]], axis=0)
    deltas = rho_cl * (rewards + gamma * values_tp1 * nonterm - values)

    def body(carry, xs):
        delta, c, nt = xs
        carry = delta + gamma * nt * c * carry
        return carry, carry

    _, acc = jax.lax.scan(body, jnp.zeros_like(last_value),
                          (deltas, c_cl, nonterm), reverse=True)
    vs = values + acc
    vs_tp1 = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    pg_adv = rho_cl * (rewards + gamma * vs_tp1 * nonterm - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


@dataclass
class IMPALAConfig(AlgorithmConfig):
    num_env_runners: int = 2
    num_envs_per_runner: int = 2
    rollout_length: int = 64
    num_rollouts_per_update: int = 2  # aggregated per learner batch
    gamma: float = 0.99
    lr: float = 1e-3
    entropy_coef: float = 0.01
    vf_coef: float = 0.25
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    hidden: tuple = (64, 64)
    num_aggregators: int = 1
    # multi-learner gradient sync (reference: learner_group.py:101); each
    # learner consumes its own aggregated batch, grads allreduce-averaged
    num_learners: int = 1
    learner_backend: str = "cpu"

    @property
    def algo_cls(self):
        return IMPALA


@ray_tpu.remote(num_cpus=1)
class _ImpalaRunner:
    """Time-major rollout sampler carrying behavior logp for V-trace."""

    def __init__(self, config_blob: bytes, worker_index: int):
        from ray_tpu._private.serialization import loads_trusted

        from ray_tpu.rl.env_runner import EpisodeTracker, make_vec_env

        # the blob is authored by the driving Algorithm (trusted producer)
        self.cfg: IMPALAConfig = loads_trusted(config_blob)
        self.envs, self.obs = make_vec_env(
            self.cfg.env, self.cfg.num_envs_per_runner,
            self.cfg.seed + worker_index * 1000)
        self._apply = None
        self._rng_seed = self.cfg.seed * 7919 + worker_index
        self.episodes = EpisodeTracker(self.cfg.num_envs_per_runner)

    def _policy(self):
        if self._apply is None:
            from ray_tpu.utils import import_jax

            jax = import_jax()

            from ray_tpu.models.actor_critic import ActorCritic

            n_act = int(self.envs.single_action_space.n)
            model = ActorCritic(n_act, self.cfg.hidden)
            self._apply = jax.jit(
                lambda params, obs: model.apply({"params": params}, obs))
        return self._apply

    def sample(self, params) -> Dict[str, np.ndarray]:
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp

        apply = self._policy()
        T, N = self.cfg.rollout_length, self.cfg.num_envs_per_runner
        obs_buf = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        key = jax.random.PRNGKey(self._rng_seed)
        self._rng_seed += 1
        for t in range(T):
            logits, _ = apply(params, jnp.asarray(self.obs, jnp.float32))
            key, sub = jax.random.split(key)
            action = jax.random.categorical(sub, logits)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, action[:, None], axis=-1)[:, 0]
            action = np.asarray(action)
            obs_buf[t] = self.obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            self.obs, rew, term, trunc, _ = self.envs.step(action)
            done = np.logical_or(term, trunc)
            rew_buf[t] = rew
            # cut the V-trace recursion at BOTH termination and truncation:
            # values may not leak across an episode boundary (obs[t+1] is the
            # next episode's reset obs under same-step autoreset). Treating
            # truncation as termination biases time-limited envs slightly but
            # keeps targets on-episode.
            done_buf[t] = done.astype(np.float32)
            self.episodes.step(rew, done)
        return {
            "obs": obs_buf, "actions": act_buf, "behavior_logp": logp_buf,
            "rewards": rew_buf, "dones": done_buf,
            "last_obs": np.asarray(self.obs, np.float32),
            "episode_returns": self.episodes.pop(),
        }


@ray_tpu.remote(num_cpus=0.5)
class _Aggregator:
    """Stacks rollouts into one [T, B] learner batch off the driver
    (reference: impala.py:133-148 aggregator actors)."""

    def stack(self, *rollouts) -> Dict[str, np.ndarray]:
        ep = np.concatenate([r["episode_returns"] for r in rollouts])
        out = {k: np.concatenate([r[k] for r in rollouts], axis=1)
               for k in ("obs", "actions", "behavior_logp", "rewards", "dones")}
        out["last_obs"] = np.concatenate(
            [r["last_obs"] for r in rollouts], axis=0)
        out["episode_returns"] = ep
        return out


class _ImpalaLearnerCore:
    """Params + optimizer + jitted V-trace update, usable in-process
    (num_learners=1) or as rank ``rank`` of a LearnerGroup — in the multi
    case each learner consumes its OWN aggregated batch and gradients are
    allreduce-averaged before apply (reference:
    rllib/core/learner/torch/torch_learner.py:524-547), so parameters stay
    identical across ranks (same seed -> same init)."""

    metric_keys = ("loss", "pg_loss", "vf_loss", "entropy", "mean_rho")

    def __init__(self, cfg, obs_dim: int, n_actions: int,
                 world_size: int = 1, rank: int = 0, group_name=None):
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.actor_critic import ActorCritic

        self.cfg = cfg
        self.world_size, self.rank, self.group_name = world_size, rank, group_name
        self.model = ActorCritic(n_actions, cfg.hidden)
        key = jax.random.PRNGKey(cfg.seed)
        self.params = self.model.init(key, jnp.zeros((1, obs_dim)))["params"]
        self.opt = optax.chain(optax.clip_by_global_norm(0.5),
                               optax.adam(cfg.lr))
        self.opt_state = self.opt.init(self.params)
        self._jax = jax
        loss_fn = self._make_loss()

        def fused(params, opt_state, extras, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, extras, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"loss": loss, **aux}

        self._fused = jax.jit(fused)

        def grad_fn(params, extras, batch, scale):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, extras, batch)
            grads = jax.tree.map(lambda g: g * scale, grads)
            scalars = jnp.stack(
                [loss] + [aux[k] for k in self.metric_keys[1:]]) * scale
            return grads, scalars

        self._grad = jax.jit(grad_fn)

        def apply_fn(params, opt_state, grads):
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._apply = jax.jit(apply_fn)

    # -- algorithm-specific pieces (APPO overrides) ---------------------

    def _make_loss(self):
        """Returns loss_fn(params, extras, batch) -> (total, aux_dict)."""
        from ray_tpu.utils import import_jax

        jax = import_jax()
        import jax.numpy as jnp

        cfg = self.cfg

        def loss_fn(params, extras, batch):
            del extras  # IMPALA has no auxiliary learner state
            T, B = batch["actions"].shape
            obs_all = jnp.concatenate(
                [batch["obs"].reshape((T * B,) + batch["obs"].shape[2:]),
                 batch["last_obs"]], axis=0)
            logits_all, values_all = self.model.apply({"params": params},
                                                      obs_all)
            logits = logits_all[: T * B].reshape(T, B, -1)
            values = values_all[: T * B].reshape(T, B)
            last_value = values_all[T * B:]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            rhos = jnp.exp(logp - batch["behavior_logp"])
            vs, pg_adv = vtrace_returns(
                values, last_value, batch["rewards"], batch["dones"], rhos,
                gamma=cfg.gamma, rho_clip=cfg.vtrace_rho_clip,
                c_clip=cfg.vtrace_c_clip)
            pg_loss = -(logp * pg_adv).mean()
            vf_loss = ((values - vs) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + cfg.vf_coef * vf_loss - cfg.entropy_coef * entropy
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_rho": rhos.mean()}

        return loss_fn

    def _extras(self):
        return ()

    def _post_update(self):
        pass

    # -- update ---------------------------------------------------------

    def update(self, batch) -> dict:
        import jax.numpy as jnp

        jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                  if k != "episode_returns"}
        if self.world_size == 1:
            self.params, self.opt_state, metrics = self._fused(
                self.params, self.opt_state, self._extras(), jbatch)
            self._post_update()
            return {k: float(v) for k, v in metrics.items()}
        from ray_tpu.rl.learner_group import sync_gradients

        grads, scalars = self._grad(self.params, self._extras(), jbatch,
                                    1.0 / self.world_size)
        grads, mvec = sync_gradients(grads, np.asarray(scalars),
                                     self.group_name)
        self.params, self.opt_state = self._apply(self.params,
                                                  self.opt_state, grads)
        self._post_update()
        return dict(zip(self.metric_keys, map(float, mvec)))

    def get_params(self):
        return self._jax.tree.map(np.asarray, self.params)

    def get_state(self) -> dict:
        to_np = self._jax.tree.map
        return {"params": to_np(np.asarray, self.params),
                "opt_state": to_np(np.asarray, self.opt_state)}

    def set_state(self, state: dict):
        self.params = state["params"]
        self.opt_state = state["opt_state"]


class IMPALA(Algorithm):
    learner_core_cls = _ImpalaLearnerCore

    def __init__(self, cfg: IMPALAConfig):
        import cloudpickle

        import gymnasium as gym

        super().__init__(cfg)
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        from ray_tpu.utils import import_jax

        self._jax = import_jax()

        probe = gym.make(cfg.env)
        obs_dim = int(np.prod(probe.observation_space.shape))
        n_actions = int(probe.action_space.n)
        probe.close()
        self.learner_group = None
        if cfg.num_learners > 1:
            if cfg.num_learners > cfg.num_env_runners:
                raise ValueError(
                    f"num_learners={cfg.num_learners} needs at least as "
                    f"many env runners (got {cfg.num_env_runners}): each "
                    f"learner consumes >=1 rollout per update")
            from ray_tpu.rl.learner_group import LearnerGroup

            core_cls = self.learner_core_cls

            def factory(rank, world_size, group_name, _cfg=cfg, _o=obs_dim,
                        _n=n_actions, _cls=core_cls):
                return _cls(_cfg, _o, _n, world_size=world_size, rank=rank,
                            group_name=group_name)

            self.learner_group = LearnerGroup(
                factory, cfg.num_learners, backend=cfg.learner_backend)
            self.core = None
        else:
            self.core = self.learner_core_cls(cfg, obs_dim, n_actions)

        blob = cloudpickle.dumps(cfg)
        self.runners = [_ImpalaRunner.remote(blob, i)
                        for i in range(cfg.num_env_runners)]
        self.aggregators = [_Aggregator.remote()
                            for _ in range(cfg.num_aggregators)]
        self._agg_rr = 0
        # prime the async pipeline: every runner starts sampling immediately
        params_np = self._current_params_np()
        self._inflight = {r.sample.remote(params_np): r for r in self.runners}
        self.env_steps = 0
        self._return_window: List[float] = []

    def _current_params_np(self):
        if self.learner_group is not None:
            return self.learner_group.get_params()
        return self.core.get_params()

    def _next_aggregator(self):
        agg = self.aggregators[self._agg_rr % len(self.aggregators)]
        self._agg_rr += 1
        return agg

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.time()
        n_learners = max(1, cfg.num_learners)
        want = min(max(cfg.num_rollouts_per_update, n_learners),
                   len(self.runners))
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=want,
                                timeout=600)
        rollout_refs = []
        params_np = self._current_params_np()
        for ref in ready:
            runner = self._inflight.pop(ref)
            rollout_refs.append(ref)
            # relaunch with current weights — the runner never idles
            self._inflight[runner.sample.remote(params_np)] = runner
        if self.learner_group is None:
            batch = ray_tpu.get(
                self._next_aggregator().stack.remote(*rollout_refs),
                timeout=600)
            self._return_window.extend(batch.pop("episode_returns").tolist())
            metrics = self.core.update(batch)
            steps = int(np.prod(batch["actions"].shape))
        else:
            # one aggregated batch per learner (round-robin over the ready
            # rollouts); gradients sync inside the group
            groups = [rollout_refs[i::n_learners] for i in range(n_learners)]
            batches = ray_tpu.get(
                [self._next_aggregator().stack.remote(*g) for g in groups],
                timeout=600)
            steps = 0
            for b in batches:
                self._return_window.extend(b.pop("episode_returns").tolist())
                steps += int(np.prod(b["actions"].shape))
            metrics = self.learner_group.update_shards(batches)
        self._return_window = self._return_window[-100:]
        self.env_steps += steps
        return {
            "episode_return_mean": (float(np.mean(self._return_window))
                                    if self._return_window else 0.0),
            "num_env_steps_sampled": self.env_steps,
            "num_rollouts_aggregated": len(rollout_refs),
            "steps_per_sec": steps / max(time.time() - t0, 1e-6),
            **{k: float(v) for k, v in metrics.items()},
        }

    def get_state(self):
        if self.learner_group is not None:
            state = self.learner_group.get_state()
        else:
            state = self.core.get_state()
        state["env_steps"] = self.env_steps
        return state

    def set_state(self, state):
        self.env_steps = state.get("env_steps", 0)
        if self.learner_group is not None:
            self.learner_group.set_state(state)
        else:
            self.core.set_state(state)

    def stop(self):
        if self.learner_group is not None:
            self.learner_group.shutdown()
        for a in list(self.runners) + list(self.aggregators):
            try:
                ray_tpu.kill(a)
            except Exception:
                pass

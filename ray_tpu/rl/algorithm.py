"""Algorithm base API (reference: rllib/algorithms/algorithm.py:212).

``AlgorithmConfig.build() -> Algorithm`` with ``train() -> result dict``,
checkpointing, and Tune-compatibility (an Algorithm is a valid trainable:
``tune.Tuner(lambda cfg: ...)`` can call train() in a loop and report).
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass
from typing import Any, Dict, Optional


@dataclass
class AlgorithmConfig:
    env: str = "CartPole-v1"
    seed: int = 0

    algo_cls = None  # set by subclasses

    def build(self) -> "Algorithm":
        if self.algo_cls is None:
            raise NotImplementedError("config does not name an algo_cls")
        return self.algo_cls(self)

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


class Algorithm:
    """Base trainable: subclasses implement ``training_step`` and params
    accessors; ``train`` adds iteration bookkeeping."""

    def __init__(self, config):
        self.config = config
        self.iteration = 0

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        result = self.training_step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        return result

    # -- checkpointing (reference: rllib/utils/checkpoints.py Checkpointable)

    def get_state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_state(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        """Commit the algorithm state through the checkpoint plane
        (``ray_tpu/ckpt``): the dir becomes a manifest + content-addressed
        chunk store, so repeated saves of a mostly-unchanged state (frozen
        nets, slowly-mutating buffers) write only the delta and a torn
        save never becomes visible."""
        from ray_tpu.ckpt import CheckpointStore, save_checkpoint

        store = CheckpointStore(checkpoint_dir, name="rl")
        save_checkpoint(store, {"iteration": self.iteration,
                                "state": self.get_state()},
                        step=self.iteration)
        return checkpoint_dir

    def restore_from_checkpoint(self, checkpoint_dir: str) -> None:
        from ray_tpu.ckpt import CheckpointStore, restore_tree

        store = CheckpointStore(checkpoint_dir, name="rl")
        if store.latest_id() is not None:
            blob = restore_tree(store)
        else:
            # pre-plane checkpoint layout: a single pickled state blob,
            # decoded only through the audited boundary (raylint SER001)
            from ray_tpu._private.serialization import loads_trusted

            path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
            with open(path, "rb") as f:
                blob = loads_trusted(f.read())
        self.iteration = blob["iteration"]
        self.set_state(blob["state"])

    def stop(self) -> None:
        pass

"""ray_tpu: a TPU-native distributed computing framework.

Tasks, actors, and a distributed object store over a TPU-topology-aware
scheduler, with collective communication lowering to XLA collectives over
ICI/DCN, plus data / train / tune / serve / RL libraries built on top.

This module intentionally does NOT import jax: the core runtime stays
lightweight so worker processes start fast; accelerator code paths
(models/ops/parallel/train) import jax lazily.
"""

from ray_tpu._private.worker import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.actor import method
from ray_tpu.object_ref import ObjectRef
from ray_tpu.runtime_context import get_runtime_context
from ray_tpu import exceptions

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "get_runtime_context",
    "cluster_resources",
    "available_resources",
    "nodes",
    "ObjectRef",
    "exceptions",
    "__version__",
]

"""@ray_tpu.remote on classes: ActorClass / ActorHandle / ActorMethod.

Reference: python/ray/actor.py (ActorClass._remote, ActorHandle,
concurrency groups, max_restarts semantics).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional

from ray_tpu._private.common import ActorOptions
from ray_tpu._private.ids import ActorID

_OPTION_FIELDS = set(ActorOptions.__dataclass_fields__)


def build_actor_options(defaults: ActorOptions, overrides: Dict[str, Any]) -> ActorOptions:
    opts = copy.copy(defaults)
    for key, value in overrides.items():
        if key in _OPTION_FIELDS:
            setattr(opts, key, value)
        else:
            raise ValueError(f"unknown actor option {key!r}")
    strat = opts.scheduling_strategy
    if strat is not None and hasattr(strat, "placement_group"):
        opts.placement_group = strat.placement_group
        opts.placement_group_bundle_index = getattr(strat, "placement_group_bundle_index", -1)
    return opts


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, **overrides) -> "ActorMethod":
        m = ActorMethod(self._handle, self._method_name, self._num_returns)
        m._overrides = overrides
        return m

    def remote(self, *args, **kwargs):
        from ray_tpu._private import worker as _worker

        overrides = getattr(self, "_overrides", {})
        return _worker.global_worker().submit_actor_task(
            self._handle, self._method_name, args, kwargs,
            num_returns=overrides.get("num_returns", self._num_returns),
            tensor_transport=overrides.get("tensor_transport", ""),
        )

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._method_name} cannot be called directly; use .remote(...)"
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_names, class_name: str = "",
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._method_names = tuple(method_names)
        self._class_name = class_name
        self._max_task_retries = max_task_retries

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("__"):
            raise AttributeError(name)
        if self._method_names and name not in self._method_names:
            raise AttributeError(
                f"actor {self._class_name or self._actor_id} has no method {name!r}"
            )
        return ActorMethod(self, name)

    def __reduce__(self):
        return (
            _rebuild_handle,
            (self._actor_id.binary(), self._method_names, self._class_name,
             self._max_task_retries),
        )

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"


def _rebuild_handle(id_bytes, method_names, class_name, max_task_retries):
    return ActorHandle(ActorID(id_bytes), method_names, class_name, max_task_retries)


class ActorClass:
    def __init__(self, cls: type, options: ActorOptions):
        self._cls = cls
        self._options = options
        self.__doc__ = cls.__doc__

    @property
    def cls(self) -> type:
        return self._cls

    @property
    def class_name(self) -> str:
        return self._cls.__name__

    @property
    def actor_options(self) -> ActorOptions:
        return self._options

    def method_names(self):
        return [
            n
            for n in dir(self._cls)
            if not n.startswith("__") and callable(getattr(self._cls, n, None))
        ]

    def options(self, **overrides) -> "ActorClass":
        return ActorClass(self._cls, build_actor_options(self._options, overrides))

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu._private import worker as _worker

        return _worker.global_worker().create_actor(self, args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self.class_name} cannot be instantiated directly; "
            f"use .remote(...)"
        )


def method(num_returns: int = 1, concurrency_group: str = "", tensor_transport: str = ""):
    """@ray_tpu.method decorator for per-method options (reference: ray.method)."""

    def decorator(fn):
        fn.__ray_tpu_num_returns__ = num_returns
        fn.__ray_tpu_concurrency_group__ = concurrency_group
        fn.__ray_tpu_tensor_transport__ = tensor_transport
        return fn

    return decorator

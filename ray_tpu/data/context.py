"""DataContext: per-driver execution knobs (reference:
python/ray/data/context.py DataContext.get_current)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

_lock = threading.Lock()
_current: Optional["DataContext"] = None


@dataclass
class DataContext:
    # rows per block targeted by sources that can choose (range/from_items)
    target_block_rows: int = 4096
    # global cap on concurrently running data tasks (None -> cluster CPUs)
    max_tasks_in_flight: Optional[int] = None
    # per-operator cap on undispatched input + output bundles before
    # upstream dispatch is throttled (streaming backpressure)
    max_buffered_bundles: int = 16
    # default partition count for shuffles/joins/groupbys (None -> #blocks)
    default_shuffle_partitions: Optional[int] = None
    # bounded consumer prefetch for iter_batches/iter_rows
    prefetch_bundles: int = 4
    # default CPU request per data task
    num_cpus_per_task: float = 1.0
    # collect per-operator stats
    enable_stats: bool = True

    @staticmethod
    def get_current() -> "DataContext":
        global _current
        with _lock:
            if _current is None:
                _current = DataContext()
            return _current

"""Remote task bodies for the data layer (execute on workers).

Reference: the fused map transform of
python/ray/data/_internal/planner/plan_udf_map_op.py and the two-phase
shuffle tasks of operators/hash_shuffle.py. Every map-family task returns
``(block, meta)`` where meta is a small dict — the executor waits on the
meta ref (inlined into the owner's memory store) for completion/stats and
streams the block ref downstream without fetching it."""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
# driver-authored UDF/spec blobs: every decode goes through the audited
# serialization boundary (raylint SER001) instead of raw cloudpickle
from ray_tpu._private.serialization import loads_trusted
from ray_tpu.data.block import Block, BlockAccessor


def _meta(block: Block, t0: float) -> dict:
    acc = BlockAccessor(block)
    return {"rows": acc.num_rows(), "bytes": acc.size_bytes(),
            "wall_s": time.perf_counter() - t0}


def apply_chain(block: Block, chain: List[tuple], init_state: dict) -> Block:
    """Run a fused chain of (kind, fn, batch_size) stages over one block.
    ``fn`` entries may be callables or constructed-class instances from
    ``init_state`` (actor-pool path)."""
    for kind, fn, batch_size in chain:
        if isinstance(fn, str):  # class-UDF: look up the constructed instance
            fn = init_state[fn]
        acc = BlockAccessor(block)
        if kind == "map_rows":
            block = BlockAccessor.build_from_rows([fn(r) for r in acc.to_rows()])
        elif kind == "flat_map":
            out: List[Any] = []
            for r in acc.to_rows():
                out.extend(fn(r))
            block = BlockAccessor.build_from_rows(out)
        elif kind == "filter":
            block = BlockAccessor.build_from_rows(
                [r for r in acc.to_rows() if fn(r)])
        elif kind == "map_batches":
            n = acc.num_rows()
            bs = batch_size or n or 1
            rows: List[Any] = []
            arrow_parts = []
            for start in range(0, n, bs):
                batch = BlockAccessor(acc.slice(start, min(start + bs, n))).to_batch()
                result = fn(batch)
                part = (BlockAccessor.build_from_batch(result)
                        if isinstance(result, dict)
                        else BlockAccessor.build_from_rows(list(result)))
                arrow_parts.append(part)
            if len(arrow_parts) == 1:
                block = arrow_parts[0]
            else:
                rows = []
                for p in arrow_parts:
                    rows.extend(BlockAccessor(p).to_rows())
                block = BlockAccessor.build_from_rows(rows)
        else:
            raise ValueError(f"unknown stage kind {kind!r}")
    return block


@ray_tpu.remote
def map_block(chain_blob: bytes, block: Block) -> Tuple[Block, dict]:
    t0 = time.perf_counter()
    chain = loads_trusted(chain_blob)
    out = apply_chain(block, chain, {})
    return out, _meta(out, t0)


@ray_tpu.remote
def read_block(thunk_blob: bytes) -> Tuple[Block, dict]:
    t0 = time.perf_counter()
    thunk = loads_trusted(thunk_blob)
    out = thunk()
    return out, _meta(out, t0)


@ray_tpu.remote
class MapWorker:
    """Actor-pool map worker: holds constructed class-UDF instances
    (reference: ActorPoolMapOperator's _MapWorker)."""

    def __init__(self, ctors_blob: bytes):
        ctors: Dict[str, tuple] = loads_trusted(ctors_blob)
        self._state = {name: cls(*args, **kwargs)
                       for name, (cls, args, kwargs) in ctors.items()}

    def map_block(self, chain_blob: bytes, block: Block) -> Tuple[Block, dict]:
        t0 = time.perf_counter()
        chain = loads_trusted(chain_blob)
        out = apply_chain(block, chain, self._state)
        return out, _meta(out, t0)

    def ping(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# all-to-all phase tasks (hash shuffle / sort / repartition)
# ---------------------------------------------------------------------------


@ray_tpu.remote
def shuffle_map(block: Block, part_fn_blob: bytes, num_parts: int) -> List[Block]:
    """Partition one block into ``num_parts`` sub-blocks (hash/range/random).
    Returns a list-block of sub-blocks (kept as ONE object; the reduce task
    indexes into it) — avoids num_returns fan-out on the object store."""
    part_fn = loads_trusted(part_fn_blob)
    acc = BlockAccessor(block)
    rows = acc.to_rows()
    parts: List[List[Any]] = [[] for _ in range(num_parts)]
    for r in rows:
        parts[part_fn(r) % num_parts].append(r)
    return [BlockAccessor.build_from_rows(p) for p in parts]


@ray_tpu.remote
def shuffle_reduce(reduce_fn_blob: bytes, part_index: int,
                   *map_outputs: List[Block]) -> Tuple[Block, dict]:
    """Concatenate partition ``part_index`` from every map output and apply
    the reduce fn (sort slice, aggregate, identity...)."""
    t0 = time.perf_counter()
    reduce_fn = loads_trusted(reduce_fn_blob)
    rows: List[Any] = []
    for parts in map_outputs:
        rows.extend(BlockAccessor(parts[part_index]).to_rows())
    out = reduce_fn(rows)
    block = BlockAccessor.build_from_rows(out) if isinstance(out, list) else out
    return block, _meta(block, t0)


@ray_tpu.remote
def sample_boundaries(key_blob: bytes, num_parts: int,
                      *blocks: Block) -> List[Any]:
    """Sample sort keys to pick range-partition boundaries."""
    key = loads_trusted(key_blob)
    samples: List[Any] = []
    for b in blocks:
        rows = BlockAccessor(b).to_rows()
        step = max(1, len(rows) // 64)
        samples.extend(key(r) for r in rows[::step])
    samples.sort()
    if not samples:
        return [None] * (num_parts - 1)
    return [samples[int(len(samples) * i / num_parts)]
            for i in range(1, num_parts)]


@ray_tpu.remote
def join_reduce(join_spec_blob: bytes, part_index: int,
                left_outputs_count: int,
                *map_outputs: List[Block]) -> Tuple[Block, dict]:
    """Hash-join one partition: the first ``left_outputs_count`` map outputs
    are the left side, the rest the right (reference: joins ride the same
    hash shuffle as groupby — operators/join.py)."""
    t0 = time.perf_counter()
    on, how, suffix = loads_trusted(join_spec_blob)
    left_rows: List[dict] = []
    right_rows: List[dict] = []
    for i, parts in enumerate(map_outputs):
        rows = BlockAccessor(parts[part_index]).to_rows()
        (left_rows if i < left_outputs_count else right_rows).extend(rows)
    index: Dict[Any, List[dict]] = {}
    for r in right_rows:
        index.setdefault(r.get(on), []).append(r)
    out: List[dict] = []
    matched_right = set()
    for l in left_rows:
        matches = index.get(l.get(on), [])
        if matches:
            for r in matches:
                matched_right.add(id(r))
                merged = dict(l)
                for k, v in r.items():
                    if k == on:
                        continue
                    merged[k + suffix if k in l and k != on else k] = v
                out.append(merged)
        elif how in ("left", "outer"):
            out.append(dict(l))
    if how in ("right", "outer"):
        for r in right_rows:
            if id(r) not in matched_right:
                out.append(dict(r))
    block = BlockAccessor.build_from_rows(out)
    return block, _meta(block, t0)


@ray_tpu.remote
def zip_aligned(left: Block, spans_blob: bytes,
                *right_blocks: Block) -> Tuple[Block, dict]:
    """Zip one left block against the right-side row ranges covering it
    ((skip, take) per right block, planned from row counts)."""
    t0 = time.perf_counter()
    spans: List[Tuple[int, int]] = loads_trusted(spans_blob)
    lrows = BlockAccessor(left).to_rows()
    rrows: List[Any] = []
    for rb, (skip, take) in zip(right_blocks, spans):
        rrows.extend(BlockAccessor(rb).to_rows()[skip:skip + take])
    if len(lrows) != len(rrows):
        raise ValueError(
            f"zip alignment bug: {len(lrows)} left vs {len(rrows)} right rows")
    out = []
    for l, r in zip(lrows, rrows):
        merged = dict(l) if isinstance(l, dict) else {"left": l}
        rd = r if isinstance(r, dict) else {"right": r}
        for k, v in rd.items():
            merged[k if k not in merged else k + "_right"] = v
        out.append(merged)
    block = BlockAccessor.build_from_rows(out)
    return block, _meta(block, t0)


@ray_tpu.remote
def slice_block(block: Block, start: int, end: int) -> Tuple[Block, dict]:
    t0 = time.perf_counter()
    out = BlockAccessor(block).slice(start, end)
    return out, _meta(out, t0)


@ray_tpu.remote
def write_block(block: Block, write_fn_blob: bytes,
                index: int) -> Tuple[Block, dict]:
    t0 = time.perf_counter()
    write_fn = loads_trusted(write_fn_blob)
    path = write_fn(block, index)
    out = BlockAccessor.build_from_rows([{"path": path}])
    return out, _meta(out, t0)

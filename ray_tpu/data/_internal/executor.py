"""The streaming executor.

Reference: python/ray/data/_internal/execution/streaming_executor.py (:66,
loop :338, step :445) and streaming_executor_state.select_operator_to_run
(:744): a driver thread pumps RefBundles through the operator topology —
dispatching tasks under a global in-flight cap and per-operator buffer
caps (backpressure), moving finished outputs downstream, and feeding a
bounded consumer queue so iteration backpressures the whole pipeline."""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.data._internal.operators import (
    AllToAllOperator,
    LimitOperator,
    PhysicalOperator,
    RefBundle,
    ZipOperator,
)
from ray_tpu.data.context import DataContext
from ray_tpu.object_ref import ObjectRef

_SENTINEL = object()


class Edge:
    __slots__ = ("src", "dst", "port")

    def __init__(self, src: PhysicalOperator, dst: PhysicalOperator,
                 port: str = "in"):
        self.src = src
        self.dst = dst
        self.port = port


class StreamingExecutor:
    def __init__(self, ops: List[PhysicalOperator], edges: List[Edge],
                 output_op: PhysicalOperator,
                 context: Optional[DataContext] = None):
        self.ops = ops
        self.edges = edges
        self.output_op = output_op
        self.ctx = context or DataContext.get_current()
        self._out_queue: "queue.Queue" = queue.Queue(
            maxsize=max(1, self.ctx.prefetch_bundles))
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._stopped = threading.Event()
        self._done_notified: Dict[Tuple[int, str], bool] = {}
        # downstream-first dispatch order (constructed upstream->downstream)
        self._dispatch_order = list(reversed(ops))
        self._upstream: Dict[int, List[PhysicalOperator]] = {}
        for e in edges:
            self._upstream.setdefault(id(e.dst), []).append(e.src)

    # -- public --------------------------------------------------------

    def start(self):
        for op in self.ops:
            op.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="data-streaming-executor")
        self._thread.start()
        return self

    def stop(self):
        self._stopped.set()
        if self._thread:
            self._thread.join(timeout=30.0)

    def iter_output(self):
        """Yields RefBundles of the output operator as they materialize."""
        while True:
            item = self._out_queue.get()
            if item is _SENTINEL:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def stats_summary(self) -> str:
        return "\n".join(op.stats.summary() for op in self.ops)

    def stats_data(self) -> list:
        """Structured per-op runtime metrics (reference:
        data/_internal/stats.py + op_runtime_metrics.py)."""
        import time as _t

        out = []
        for op in self.ops:
            st = op.stats
            wall = (st.end_ts or _t.time()) - (st.start_ts or _t.time())
            out.append({"op": st.name, "tasks": st.tasks,
                        "rows_out": st.rows_out, "bytes_out": st.bytes_out,
                        "task_wall_s": round(st.task_wall_s, 4),
                        "wall_s": round(wall, 4)})
        return out

    # -- loop ----------------------------------------------------------

    def _global_cap(self) -> int:
        if self.ctx.max_tasks_in_flight:
            return self.ctx.max_tasks_in_flight
        try:
            cpus = ray_tpu.cluster_resources().get("CPU", 4.0)
        except Exception:
            cpus = 4.0
        return max(2, int(cpus * 1.5))

    def _run(self):
        try:
            self._pump()
        except BaseException as e:  # surface to the consumer
            self._error = e
        finally:
            for op in self.ops:
                try:
                    op.shutdown()
                except Exception:
                    pass
            # never block forever on a full queue: an abandoning consumer
            # (schema()/take() closing the stream early) sets _stopped and
            # will not read again
            while True:
                try:
                    self._out_queue.put_nowait(_SENTINEL)
                    break
                except queue.Full:
                    if self._stopped.is_set():
                        break
                    time.sleep(0.01)

    def _upstream_of(self, op) -> List[PhysicalOperator]:
        return self._upstream.get(id(op), [])

    def _halted_ops(self) -> set:
        """Ops transitively upstream of a satisfied Limit: their output can
        never be needed again, so stop dispatching them (early stop)."""
        halted: set = set()
        frontier = [op for op in self.ops
                    if isinstance(op, LimitOperator) and op.satisfied]
        while frontier:
            dst = frontier.pop()
            for e in self.edges:
                if e.dst is dst and id(e.src) not in halted:
                    halted.add(id(e.src))
                    frontier.append(e.src)
        return halted

    def _pump(self):
        cap = self._global_cap()
        max_buf = self.ctx.max_buffered_bundles
        waitmap: Dict[ObjectRef, PhysicalOperator] = {}

        while not self._stopped.is_set():
            progressed = False

            # 1. propagate outputs downstream / to the consumer queue
            for e in self.edges:
                dst_busy = len(e.dst.inqueue) if hasattr(e.dst, "inqueue") else 0
                while e.src.outqueue and dst_busy < max_buf:
                    bundle = e.src.outqueue.popleft()
                    if e.port == "left":
                        e.dst.add_left(bundle)
                    elif e.port == "right":
                        e.dst.add_right(bundle)
                    else:
                        e.dst.add_input(bundle)
                    dst_busy += 1
                    progressed = True
            while self.output_op.outqueue:
                try:
                    self._out_queue.put_nowait(self.output_op.outqueue[0])
                    self.output_op.outqueue.popleft()
                    progressed = True
                except queue.Full:
                    break

            # 2. propagate inputs-done markers once a src fully drains
            halted = self._halted_ops()
            for e in self.edges:
                key = (id(e.src), id(e.dst), e.port)
                if self._done_notified.get(key):
                    continue
                if id(e.src) in halted or (
                        e.src.inputs_done and not e.src.work_remaining()
                        and not e.src.outqueue):
                    self._done_notified[key] = True
                    if isinstance(e.dst, ZipOperator):
                        if e.port == "left":
                            e.dst.left_done = True
                        else:
                            e.dst.right_done = True
                        if e.dst.left_done and e.dst.right_done:
                            e.dst.notify_inputs_done()
                    else:
                        self._count_done(e.dst)
                    progressed = True

            # 3. dispatch, downstream-first, under caps
            inflight = sum(op.num_active for op in self.ops)
            for op in self._dispatch_order:
                if id(op) in halted:
                    continue
                while (inflight < cap and op.can_dispatch()
                       and len(op.outqueue) < max_buf):
                    refs = op.dispatch_one()
                    for r in refs:
                        waitmap[r] = op
                    inflight += 1
                    progressed = True
                # barrier prepare-tasks need polling even with no dispatch
                if isinstance(op, AllToAllOperator):
                    for r in op.wait_refs():
                        if r not in waitmap:
                            waitmap[r] = op

            # 4. termination
            if self.output_op.is_finished() and not self.output_op.outqueue:
                self._check_drained()
                return
            # an output op that can't make progress anymore (e.g. satisfied
            # limit with drained queues)
            if (isinstance(self.output_op, LimitOperator)
                    and self.output_op.satisfied
                    and not self.output_op.work_remaining()
                    and not self.output_op.outqueue):
                return

            # 5. wait for some task to finish
            if waitmap:
                ready, _ = ray_tpu.wait(list(waitmap.keys()), num_returns=1,
                                        timeout=0.2 if progressed else 1.0)
                for ref in ready:
                    op = waitmap.pop(ref)
                    op.on_task_done(ref)
                    progressed = True
            elif not progressed:
                time.sleep(0.005)

    def _check_drained(self):
        """Invariant at clean termination: nothing buffered anywhere. A
        violation means bundles would be silently dropped — fail loudly."""
        halted = self._halted_ops()
        for op in self.ops:
            if id(op) in halted or op is self.output_op:
                continue
            leftovers = []
            if getattr(op, "_seq_buf", None):
                leftovers.append(f"seq_buf={list(op._seq_buf)}")
            if getattr(op, "_ordered_buf", None):
                leftovers.append(f"ordered_buf={list(op._ordered_buf)}")
            if op._active:
                leftovers.append(f"active={len(op._active)}")
            if op.outqueue:
                leftovers.append(f"outqueue={len(op.outqueue)}")
            if op.work_remaining():
                leftovers.append("work_remaining")
            if leftovers:
                raise RuntimeError(
                    f"streaming executor terminated with undrained operator "
                    f"{op.name}: {', '.join(leftovers)} — this is a bug; "
                    f"bundles would have been dropped")

    def _count_done(self, dst: PhysicalOperator):
        """Mark dst inputs-done once EVERY upstream edge has finished."""
        for e in self.edges:
            if e.dst is dst and not self._done_notified.get(
                    (id(e.src), id(e.dst), e.port)):
                return
        dst.notify_inputs_done()

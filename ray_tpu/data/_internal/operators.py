"""Physical operators for the streaming executor.

Reference: python/ray/data/_internal/execution/operators/ —
TaskPoolMapOperator / ActorPoolMapOperator (map_operator.py),
AllToAllOperator (all_to_all_operator.py) backing shuffle/sort/groupby,
hash-shuffle two-phase fan (hash_shuffle.py), LimitOperator, ZipOperator,
UnionOperator, and the RefBundle currency (interfaces/ref_bundle.py).

Data moves as ``RefBundle``s: object refs to blocks plus their (already
resolved) row/byte counts. Operators never fetch block contents — only the
small meta dicts travel to the driver."""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.data._internal import tasks as T
from ray_tpu.object_ref import ObjectRef


class RefBundle:
    __slots__ = ("block", "rows", "bytes")

    def __init__(self, block: ObjectRef, rows: Optional[int] = None,
                 nbytes: int = 0):
        self.block = block
        self.rows = rows
        self.bytes = nbytes

    def __repr__(self):
        return f"RefBundle(rows={self.rows})"


class OpStats:
    __slots__ = ("name", "tasks", "rows_out", "bytes_out", "task_wall_s",
                 "start_ts", "end_ts")

    def __init__(self, name: str):
        self.name = name
        self.tasks = 0
        self.rows_out = 0
        self.bytes_out = 0
        self.task_wall_s = 0.0
        self.start_ts: Optional[float] = None
        self.end_ts: Optional[float] = None

    def record(self, meta: dict):
        self.tasks += 1
        self.rows_out += meta.get("rows", 0)
        self.bytes_out += meta.get("bytes", 0)
        self.task_wall_s += meta.get("wall_s", 0.0)
        self.end_ts = time.time()

    def summary(self) -> str:
        wall = (self.end_ts or time.time()) - (self.start_ts or time.time())
        return (f"{self.name}: {self.tasks} tasks, {self.rows_out} rows, "
                f"{self.bytes_out / 1e6:.2f} MB, task-time {self.task_wall_s:.2f}s, "
                f"wall {wall:.2f}s")


class PhysicalOperator:
    """Base: push-based input, pull-based output, task-parallel inside."""

    def __init__(self, name: str, num_cpus: float = 1.0,
                 concurrency: Optional[int] = None):
        self.name = name
        self.num_cpus = num_cpus
        self.concurrency = concurrency  # per-op task cap (None -> global only)
        self.inqueue: collections.deque = collections.deque()
        self.outqueue: collections.deque = collections.deque()
        self.inputs_done = False
        self._active: Dict[ObjectRef, Any] = {}  # wait-ref -> task record
        self.stats = OpStats(name)
        # datasets are ordered: tasks may finish out of order, so emissions
        # are sequenced (reference: bundle ordering in the map operators)
        self._seq_dispatch = 0
        self._seq_emit = 0
        self._seq_buf: Dict[int, RefBundle] = {}

    # -- lifecycle -----------------------------------------------------

    def start(self):
        self.stats.start_ts = time.time()

    def shutdown(self):
        pass

    # -- scheduling ----------------------------------------------------

    def add_input(self, bundle: RefBundle):
        self.inqueue.append(bundle)

    def notify_inputs_done(self):
        self.inputs_done = True

    def can_dispatch(self) -> bool:
        if self.concurrency is not None and len(self._active) >= self.concurrency:
            return False
        return self._has_dispatchable()

    def _has_dispatchable(self) -> bool:
        return bool(self.inqueue)

    def dispatch_one(self) -> List[ObjectRef]:
        """Submit one task; returns refs the executor should wait on."""
        raise NotImplementedError

    def on_task_done(self, ref: ObjectRef):
        raise NotImplementedError

    @property
    def num_active(self) -> int:
        return len(self._active)

    def _take_seq(self) -> int:
        s = self._seq_dispatch
        self._seq_dispatch += 1
        return s

    def _emit(self, seq: int, bundle: RefBundle):
        self._seq_buf[seq] = bundle
        while self._seq_emit in self._seq_buf:
            self.outqueue.append(self._seq_buf.pop(self._seq_emit))
            self._seq_emit += 1

    def is_finished(self) -> bool:
        return (self.inputs_done and not self.inqueue and not self._active
                and not self._seq_buf and not self.outqueue)

    def work_remaining(self) -> bool:
        return bool(self.inqueue or self._active or self._seq_buf)


class ReadOperator(PhysicalOperator):
    """Source: one read task per thunk (reference: InputDataBuffer + the
    read tasks planned by planner/plan_read_op.py)."""

    def __init__(self, thunks: List[bytes], num_cpus: float = 1.0,
                 concurrency: Optional[int] = None):
        super().__init__("Read", num_cpus, concurrency)
        self._thunks = collections.deque(thunks)
        self.inputs_done = True

    def _has_dispatchable(self) -> bool:
        return bool(self._thunks)

    def dispatch_one(self) -> List[ObjectRef]:
        thunk = self._thunks.popleft()
        block_ref, meta_ref = T.read_block.options(
            num_returns=2, num_cpus=self.num_cpus).remote(thunk)
        self._active[meta_ref] = (block_ref, self._take_seq())
        return [meta_ref]

    def on_task_done(self, meta_ref: ObjectRef):
        block_ref, seq = self._active.pop(meta_ref)
        meta = ray_tpu.get(meta_ref)
        self.stats.record(meta)
        self._emit(seq, RefBundle(block_ref, meta["rows"], meta["bytes"]))

    def is_finished(self) -> bool:
        return (not self._thunks and not self._active
                and not self._seq_buf and not self.outqueue)

    def work_remaining(self) -> bool:
        return bool(self._thunks or self._active or self._seq_buf)


class InputDataOperator(PhysicalOperator):
    """Source over already-materialized block refs."""

    def __init__(self, bundles: List[RefBundle]):
        super().__init__("InputData")
        self.outqueue.extend(bundles)
        self.inputs_done = True

    def _has_dispatchable(self) -> bool:
        return False

    def dispatch_one(self):  # pragma: no cover
        raise AssertionError

    def is_finished(self) -> bool:
        return not self.outqueue

    def work_remaining(self) -> bool:
        return False


class TaskPoolMapOperator(PhysicalOperator):
    """Fused map chain executed as one stateless task per block."""

    def __init__(self, name: str, chain: List[tuple], num_cpus: float = 1.0,
                 concurrency: Optional[int] = None):
        super().__init__(name, num_cpus, concurrency)
        self._chain_blob = cloudpickle.dumps(chain)

    def dispatch_one(self) -> List[ObjectRef]:
        bundle: RefBundle = self.inqueue.popleft()
        block_ref, meta_ref = T.map_block.options(
            num_returns=2, num_cpus=self.num_cpus).remote(
                self._chain_blob, bundle.block)
        self._active[meta_ref] = (block_ref, self._take_seq())
        return [meta_ref]

    def on_task_done(self, meta_ref: ObjectRef):
        block_ref, seq = self._active.pop(meta_ref)
        meta = ray_tpu.get(meta_ref)
        self.stats.record(meta)
        self._emit(seq, RefBundle(block_ref, meta["rows"], meta["bytes"]))


class ActorPoolMapOperator(PhysicalOperator):
    """Map chain with stateful class-UDFs on a pool of actors
    (reference: ActorPoolMapOperator + _ActorPool autoscaling)."""

    def __init__(self, name: str, chain: List[tuple],
                 ctors: Dict[str, tuple], pool_size: int = 2,
                 num_cpus: float = 1.0):
        super().__init__(name, num_cpus, concurrency=None)
        self._chain_blob = cloudpickle.dumps(chain)
        self._ctors_blob = cloudpickle.dumps(ctors)
        self._pool_size = pool_size
        self._actors: List[Any] = []
        self._idle: collections.deque = collections.deque()

    def start(self):
        super().start()
        for _ in range(self._pool_size):
            actor = T.MapWorker.options(num_cpus=self.num_cpus).remote(
                self._ctors_blob)
            self._actors.append(actor)
            # each actor can run a small pipeline of calls
            self._idle.extend([actor, actor])

    def shutdown(self):
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors.clear()

    def can_dispatch(self) -> bool:
        return bool(self.inqueue) and bool(self._idle)

    def dispatch_one(self) -> List[ObjectRef]:
        bundle: RefBundle = self.inqueue.popleft()
        actor = self._idle.popleft()
        block_ref, meta_ref = actor.map_block.options(num_returns=2).remote(
            self._chain_blob, bundle.block)
        self._active[meta_ref] = (block_ref, actor, self._take_seq())
        return [meta_ref]

    def on_task_done(self, meta_ref: ObjectRef):
        block_ref, actor, seq = self._active.pop(meta_ref)
        self._idle.append(actor)
        meta = ray_tpu.get(meta_ref)
        self.stats.record(meta)
        self._emit(seq, RefBundle(block_ref, meta["rows"], meta["bytes"]))


class AllToAllOperator(PhysicalOperator):
    """Barrier operator running a two-phase hash/range shuffle.

    Phase 1 (map): partition every input block into N parts.
    Phase 2 (reduce): per partition, concat its parts from all maps and
    apply the reduce fn. ``prepare`` optionally computes shared state (e.g.
    sampled sort boundaries) from the materialized inputs first."""

    def __init__(self, name: str, num_partitions: Optional[int],
                 part_fn_factory: Callable[[Any], Callable],
                 reduce_fn_factory: Callable[[Any], Callable],
                 prepare: Optional[Callable] = None,
                 num_cpus: float = 1.0):
        super().__init__(name, num_cpus)
        self._num_partitions = num_partitions
        self._part_fn_factory = part_fn_factory
        self._reduce_fn_factory = reduce_fn_factory
        self._prepare = prepare
        self._input_bundles: List[RefBundle] = []
        self._phase = "collect"  # collect -> prepare -> map -> reduce
        self._prepare_ref: Optional[ObjectRef] = None
        self._prepared_state: Any = None
        self._map_pending: collections.deque = collections.deque()
        self._map_outputs: List[ObjectRef] = []
        self._maps_in_flight: Dict[ObjectRef, int] = {}
        self._reduce_pending: collections.deque = collections.deque()
        # ordered emission (sort): buffer finished partitions and release
        # them in partition order so the global order is preserved
        self.ordered = False
        self.reverse_order = False
        self._ordered_buf: Dict[int, RefBundle] = {}
        self._next_emit = 0

    def add_input(self, bundle: RefBundle):
        self._input_bundles.append(bundle)

    def _n_parts(self) -> int:
        if self._num_partitions:
            return self._num_partitions
        return max(1, len(self._input_bundles))

    def _advance_phase(self):
        if self._phase == "collect" and self.inputs_done:
            if self._prepare is not None:
                self._phase = "prepare"
                self._prepare_ref = self._prepare(
                    self._input_bundles, self._n_parts())
            else:
                self._start_map(None)

    def _start_map(self, state):
        self._prepared_state = state
        part_fn = self._part_fn_factory(state)
        self._part_blob = cloudpickle.dumps(part_fn)
        self._phase = "map"
        self._map_pending.extend(self._input_bundles)
        if not self._map_pending:
            # zero inputs: go straight to reduce (it emits empty blocks)
            self._on_all_maps_done()
            self._phase = "reduce"
            order = range(self._n_parts())
            self._reduce_pending.extend(
                reversed(order) if self.reverse_order else order)
            if self.reverse_order:
                self._next_emit = self._n_parts() - 1

    def _has_dispatchable(self) -> bool:
        self._advance_phase()
        if self._phase == "prepare":
            return False  # waiting on the prepare task
        return bool(self._map_pending or self._reduce_pending)

    def can_dispatch(self) -> bool:
        self._advance_phase()
        if self._phase == "prepare":
            return False
        return self._has_dispatchable()

    def wait_refs(self) -> List[ObjectRef]:
        """Extra refs (prepare task) the executor must poll."""
        return [self._prepare_ref] if (
            self._phase == "prepare" and self._prepare_ref is not None) else []

    def _on_map_done(self, map_ref: ObjectRef, bundle: RefBundle):
        self._map_outputs.append(map_ref)

    def _on_all_maps_done(self):
        pass

    def dispatch_one(self) -> List[ObjectRef]:
        if self._map_pending:
            bundle: RefBundle = self._map_pending.popleft()
            ref = T.shuffle_map.options(num_cpus=self.num_cpus).remote(
                bundle.block, self._part_blob, self._n_parts())
            self._maps_in_flight[ref] = 1
            self._active[ref] = ("map", ref, bundle)
            return [ref]
        part_index = self._reduce_pending.popleft()
        reduce_fn = self._reduce_fn_factory(self._prepared_state)
        block_ref, meta_ref = T.shuffle_reduce.options(
            num_returns=2, num_cpus=self.num_cpus).remote(
                cloudpickle.dumps(reduce_fn), part_index, *self._map_outputs)
        self._active[meta_ref] = ("reduce", block_ref, part_index)
        return [meta_ref]

    def on_task_done(self, ref: ObjectRef):
        if self._phase == "prepare" and ref is self._prepare_ref:
            state = ray_tpu.get(ref)
            self._prepare_ref = None
            self._start_map(state)
            return
        record = self._active.pop(ref)
        if record[0] == "map":
            self._maps_in_flight.pop(ref, None)
            self._on_map_done(record[1], record[2])
            if not self._map_pending and not self._maps_in_flight:
                self._on_all_maps_done()
                self._phase = "reduce"
                order = range(self._n_parts())
                self._reduce_pending.extend(
                    reversed(order) if self.reverse_order else order)
                if self.reverse_order:
                    self._next_emit = self._n_parts() - 1
        else:
            _, block_ref, part_index = record
            meta = ray_tpu.get(ref)
            self.stats.record(meta)
            bundle = RefBundle(block_ref, meta["rows"], meta["bytes"])
            if not self.ordered:
                self.outqueue.append(bundle)
                return
            self._ordered_buf[part_index] = bundle
            step = -1 if self.reverse_order else 1
            while self._next_emit in self._ordered_buf:
                self.outqueue.append(self._ordered_buf.pop(self._next_emit))
                self._next_emit += step

    def is_finished(self) -> bool:
        return (self.inputs_done and self._phase == "reduce"
                and not self._reduce_pending and not self._active
                and not self._ordered_buf and not self.outqueue)

    def work_remaining(self) -> bool:
        if not self.inputs_done:
            return True
        return (self._phase in ("collect", "prepare", "map")
                or bool(self._reduce_pending or self._active
                        or self._ordered_buf))


class LimitOperator(PhysicalOperator):
    """Truncates the stream after n rows; downstream of it the executor
    stops feeding once satisfied (early-stop backpressure)."""

    def __init__(self, n: int):
        super().__init__(f"Limit[{n}]")
        self._remaining = n
        self._slicing: Dict[ObjectRef, ObjectRef] = {}

    @property
    def satisfied(self) -> bool:
        return self._remaining <= 0

    def _has_dispatchable(self) -> bool:
        return bool(self.inqueue) and not self.satisfied

    def dispatch_one(self) -> List[ObjectRef]:
        bundle: RefBundle = self.inqueue.popleft()
        if bundle.rows is not None and bundle.rows <= self._remaining:
            self._remaining -= bundle.rows
            self.outqueue.append(bundle)
            return []
        take = self._remaining
        self._remaining = 0
        block_ref, meta_ref = T.slice_block.options(num_returns=2).remote(
            bundle.block, 0, take)
        self._active[meta_ref] = block_ref
        return [meta_ref]

    def on_task_done(self, meta_ref: ObjectRef):
        # the slice is always the final emission; direct append keeps order
        block_ref = self._active.pop(meta_ref)
        meta = ray_tpu.get(meta_ref)
        self.stats.record(meta)
        self.outqueue.append(RefBundle(block_ref, meta["rows"], meta["bytes"]))

    def is_finished(self) -> bool:
        return ((self.satisfied or (self.inputs_done and not self.inqueue))
                and not self._active and not self.outqueue)

    def work_remaining(self) -> bool:
        if self.satisfied:
            # leftover queued inputs are abandoned, not work
            return bool(self._active)
        return bool(self.inqueue or self._active)


class UnionOperator(PhysicalOperator):
    """Streams bundles from all upstreams through unchanged."""

    def __init__(self):
        super().__init__("Union")

    def add_input(self, bundle: RefBundle):
        self.outqueue.append(bundle)

    def _has_dispatchable(self) -> bool:
        return False

    def dispatch_one(self):  # pragma: no cover
        raise AssertionError

    def is_finished(self) -> bool:
        return self.inputs_done and not self.outqueue

    def work_remaining(self) -> bool:
        return False


class ZipOperator(PhysicalOperator):
    """Row-aligned zip of two upstreams. A barrier: left and right block
    structures may differ (different parallelism, filters...), so alignment
    is planned from row counts once both sides are complete — the i-th left
    block is zipped against the right ROW RANGE it covers (reference:
    ZipOperator aligns on rows, not blocks)."""

    def __init__(self):
        super().__init__("Zip")
        self._left: List[RefBundle] = []
        self._right: List[RefBundle] = []
        self.left_done = False
        self.right_done = False
        self._planned = False
        self._pending: collections.deque = collections.deque()

    def add_left(self, bundle: RefBundle):
        self._left.append(bundle)

    def add_right(self, bundle: RefBundle):
        self._right.append(bundle)

    def _plan(self):
        if self._planned or not self.inputs_done:
            return
        self._planned = True
        n_left = sum(b.rows or 0 for b in self._left)
        n_right = sum(b.rows or 0 for b in self._right)
        if n_left != n_right:
            raise ValueError(
                f"zip requires equal row counts; left has {n_left}, "
                f"right has {n_right}")
        # for each left block [lo, hi): the right blocks + offsets covering it
        right_bounds = []
        pos = 0
        for b in self._right:
            right_bounds.append((pos, pos + (b.rows or 0), b))
            pos += b.rows or 0
        lo = 0
        for lb in self._left:
            hi = lo + (lb.rows or 0)
            picks = []  # (bundle, skip, take)
            for rlo, rhi, rb in right_bounds:
                s, e = max(lo, rlo), min(hi, rhi)
                if s < e:
                    picks.append((rb.block, s - rlo, e - s))
            self._pending.append((lb, picks))
            lo = hi

    def _has_dispatchable(self) -> bool:
        self._plan()
        return bool(self._pending)

    def can_dispatch(self) -> bool:
        return self._has_dispatchable()

    def dispatch_one(self) -> List[ObjectRef]:
        lb, picks = self._pending.popleft()
        spans = [(skip, take) for _, skip, take in picks]
        right_blocks = [ref for ref, _, _ in picks]
        block_ref, meta_ref = T.zip_aligned.options(num_returns=2).remote(
            lb.block, cloudpickle.dumps(spans), *right_blocks)
        self._active[meta_ref] = (block_ref, self._take_seq())
        return [meta_ref]

    def on_task_done(self, meta_ref: ObjectRef):
        block_ref, seq = self._active.pop(meta_ref)
        meta = ray_tpu.get(meta_ref)
        self.stats.record(meta)
        self._emit(seq, RefBundle(block_ref, meta["rows"], meta["bytes"]))

    def is_finished(self) -> bool:
        return (self.inputs_done and self._planned and not self._pending
                and not self._active and not self._seq_buf
                and not self.outqueue)

    def work_remaining(self) -> bool:
        if not self.inputs_done:
            return True
        return bool(not self._planned or self._pending or self._active
                    or self._seq_buf)


class WriteOperator(PhysicalOperator):
    """One write task per block; emits {'path': ...} rows."""

    def __init__(self, write_fn: Callable, num_cpus: float = 1.0):
        super().__init__("Write", num_cpus)
        self._write_blob = cloudpickle.dumps(write_fn)
        self._index = 0

    def dispatch_one(self) -> List[ObjectRef]:
        bundle: RefBundle = self.inqueue.popleft()
        idx = self._index
        self._index += 1
        block_ref, meta_ref = T.write_block.options(
            num_returns=2, num_cpus=self.num_cpus).remote(
                bundle.block, self._write_blob, idx)
        self._active[meta_ref] = (block_ref, self._take_seq())
        return [meta_ref]

    def on_task_done(self, meta_ref: ObjectRef):
        block_ref, seq = self._active.pop(meta_ref)
        meta = ray_tpu.get(meta_ref)
        self.stats.record(meta)
        self._emit(seq, RefBundle(block_ref, meta["rows"], meta["bytes"]))

"""Additional datasources: TFRecord, WebDataset, SQL, HuggingFace.

Reference: python/ray/data/datasource/ — ``tfrecords_datasource.py``,
``webdataset_datasource.py``, ``sql_datasource.py``, ``read_api.py``
``from_huggingface``. TPU-first notes: TFRecord framing + the
``tf.train.Example`` proto are parsed/emitted with a self-contained wire
codec (no tensorflow dependency in the image), WebDataset shards are plain
tarfiles, and SQL rides any DB-API connection factory.
"""

from __future__ import annotations

import functools
import io
import struct
import tarfile
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import Dataset, _expand_paths, _make_dataset

# ---------------------------------------------------------------------------
# TFRecord (record framing: tensorflow/core/lib/io/record_writer.cc;
# payloads: tf.train.Example protos)
# ---------------------------------------------------------------------------

_CRC_TABLE: Optional[List[int]] = None


def _crc32c(data: bytes) -> int:
    """Castagnoli CRC (table-driven); TFRecord masks it per record."""
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


def _write_varint(out: bytearray, v: int):
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    out = bytearray()
    _write_varint(out, (field << 3) | wire)
    return bytes(out)


def _len_delim(field: int, payload: bytes) -> bytes:
    out = bytearray(_tag(field, 2))
    _write_varint(out, len(payload))
    out += payload
    return bytes(out)


def _encode_feature(value) -> bytes:
    """tf.train.Feature: 1=BytesList 2=FloatList 3=Int64List."""
    if isinstance(value, (bytes, str)):
        value = [value]
    elif isinstance(value, np.ndarray):
        value = value.tolist()
    elif not isinstance(value, (list, tuple)):
        value = [value]
    first = value[0] if value else 0
    if isinstance(first, (bytes, str)):
        inner = b"".join(
            _len_delim(1, v.encode() if isinstance(v, str) else v)
            for v in value)
        return _len_delim(1, inner)
    if isinstance(first, (float, np.floating)):
        packed = struct.pack(f"<{len(value)}f", *[float(v) for v in value])
        return _len_delim(2, _tag(1, 2) + _varint_bytes(len(packed)) + packed)
    packed = bytearray()
    for v in value:
        _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
    return _len_delim(3, _tag(1, 2) + _varint_bytes(len(packed))
                      + bytes(packed))


def _varint_bytes(v: int) -> bytes:
    out = bytearray()
    _write_varint(out, v)
    return bytes(out)


def encode_example(row: Dict[str, Any]) -> bytes:
    """Serialize one row as a tf.train.Example proto."""
    entries = b"".join(
        _len_delim(1, _len_delim(1, k.encode()) + _len_delim(
            2, _encode_feature(v)))
        for k, v in row.items())
    return _len_delim(1, entries)  # Example.features


def _parse_packed_floats(buf: bytes) -> List[float]:
    return list(struct.unpack(f"<{len(buf) // 4}f", buf))


def _parse_feature(buf: bytes):
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        ln, pos = _read_varint(buf, pos)
        body = buf[pos:pos + ln]
        pos += ln
        if field == 1:  # BytesList
            out, p = [], 0
            while p < len(body):
                t, p = _read_varint(body, p)
                n, p = _read_varint(body, p)
                out.append(body[p:p + n])
                p += n
            return out[0] if len(out) == 1 else out
        if field == 2:  # FloatList (packed)
            p = 0
            vals: List[float] = []
            while p < len(body):
                t, p = _read_varint(body, p)
                if (t & 7) == 2:
                    n, p = _read_varint(body, p)
                    vals.extend(_parse_packed_floats(body[p:p + n]))
                    p += n
                else:
                    vals.append(struct.unpack("<f", body[p:p + 4])[0])
                    p += 4
            return vals[0] if len(vals) == 1 else vals
        if field == 3:  # Int64List (packed varints)
            p = 0
            ints: List[int] = []
            while p < len(body):
                t, p = _read_varint(body, p)
                if (t & 7) == 2:
                    n, p = _read_varint(body, p)
                    q = p
                    while q < p + n:
                        v, q = _read_varint(body, q)
                        if v >= 1 << 63:
                            v -= 1 << 64
                        ints.append(v)
                    p += n
                else:
                    v, p = _read_varint(body, p)
                    if v >= 1 << 63:
                        v -= 1 << 64
                    ints.append(v)
            return ints[0] if len(ints) == 1 else ints
    return None


def decode_example(buf: bytes) -> Dict[str, Any]:
    row: Dict[str, Any] = {}
    pos = 0
    tag, pos = _read_varint(buf, pos)  # Example.features
    ln, pos = _read_varint(buf, pos)
    feats = buf[pos:pos + ln]
    pos = 0
    while pos < len(feats):
        tag, pos = _read_varint(feats, pos)
        ln, pos = _read_varint(feats, pos)
        entry = feats[pos:pos + ln]
        pos += ln
        key = value = None
        p = 0
        while p < len(entry):
            t, p = _read_varint(entry, p)
            n, p = _read_varint(entry, p)
            body = entry[p:p + n]
            p += n
            if (t >> 3) == 1:
                key = body.decode()
            else:
                value = _parse_feature(body)
        if key is not None:
            row[key] = value
    return row


def _read_tfrecord_file(path: str) -> Block:
    rows = []
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                break
            (length,) = struct.unpack("<Q", header[:8])
            data = f.read(length)
            f.read(4)  # data crc (not verified on read, like the reference)
            rows.append(decode_example(data))
    return BlockAccessor.build_from_rows(rows)


def read_tfrecords(paths, parallelism: int = 8) -> Dataset:
    """Reference: data/datasource/tfrecords_datasource.py (sans tf dep)."""
    files = _expand_paths(paths, (".tfrecords", ".tfrecord"))
    return _make_dataset(
        [functools.partial(_read_tfrecord_file, f) for f in files])


def write_tfrecords(rows: List[Dict[str, Any]], path: str):
    """Emit a TFRecord file readable by tensorflow (masked crc32c frames)."""
    with open(path, "wb") as f:
        for row in rows:
            data = encode_example(row)
            hdr = struct.pack("<Q", len(data))
            f.write(hdr)
            f.write(struct.pack("<I", _masked_crc(hdr)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))


# ---------------------------------------------------------------------------
# WebDataset (tar shards, files grouped by key prefix;
# reference: data/datasource/webdataset_datasource.py)
# ---------------------------------------------------------------------------


def _read_webdataset_shard(path: str) -> Block:
    rows: List[Dict[str, Any]] = []
    current: Dict[str, Any] = {}
    key = None
    with tarfile.open(path, "r") as tar:
        for member in tar:
            if not member.isfile():
                continue
            base = member.name.split("/")[-1]
            k, _, suffix = base.partition(".")
            if key is not None and k != key:
                rows.append(current)
                current = {}
            key = k
            data = tar.extractfile(member).read()
            if suffix in ("txt", "cls", "json"):
                try:
                    data = data.decode()
                except UnicodeDecodeError:
                    pass
            current.setdefault("__key__", key)
            current[suffix] = data
    if current:
        rows.append(current)
    return BlockAccessor.build_from_rows(rows)


def read_webdataset(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, (".tar",))
    return _make_dataset(
        [functools.partial(_read_webdataset_shard, f) for f in files])


def write_webdataset(rows: List[Dict[str, Any]], path: str):
    with tarfile.open(path, "w") as tar:
        for i, row in enumerate(rows):
            key = row.get("__key__", f"{i:06d}")
            for suffix, value in row.items():
                if suffix == "__key__":
                    continue
                data = value.encode() if isinstance(value, str) else value
                info = tarfile.TarInfo(name=f"{key}.{suffix}")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))


# ---------------------------------------------------------------------------
# SQL (reference: data/datasource/sql_datasource.py — any DB-API factory)
# ---------------------------------------------------------------------------


def read_sql(sql: str, connection_factory: Callable[[], Any],
             parallelism: int = 1) -> Dataset:
    def _read() -> Block:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = [dict(zip(cols, r)) for r in cur.fetchall()]
            return BlockAccessor.build_from_rows(rows)
        finally:
            conn.close()

    return _make_dataset([_read])


# ---------------------------------------------------------------------------
# HuggingFace datasets (reference: read_api.py from_huggingface)
# ---------------------------------------------------------------------------


def from_huggingface(hf_dataset, parallelism: int = 8) -> Dataset:
    import ray_tpu.data as rdata

    try:
        table = hf_dataset.data.table  # arrow-backed: zero-copy blocks
        from ray_tpu.data.dataset import from_blocks

        n = max(1, min(parallelism, table.num_rows or 1))
        step = -(-max(table.num_rows, 1) // n)
        return from_blocks([table.slice(i, step)
                            for i in range(0, table.num_rows, step)])
    except AttributeError:
        return rdata.from_items(list(hf_dataset))

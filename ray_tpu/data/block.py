"""Blocks: the unit of data movement (reference: python/ray/data/block.py).

A block is a pyarrow Table (tabular path, zero-copy through the object
store's out-of-band buffers) or a plain Python list (object path). Batches
surface as dicts of numpy arrays (the format TPU input pipelines consume).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np

Block = Union["pyarrow.Table", List[Any]]  # noqa: F821


def _pa():
    import pyarrow

    return pyarrow


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def build_from_rows(rows: List[Any]) -> Block:
        """Rows of dicts -> arrow table; anything else -> list block."""
        if rows and all(isinstance(r, dict) for r in rows):
            try:
                return _pa().Table.from_pylist(rows)
            except Exception:
                return list(rows)
        return list(rows)

    @staticmethod
    def build_from_batch(batch: Dict[str, np.ndarray]) -> Block:
        cols = {k: np.asarray(v) for k, v in batch.items()}
        try:
            return _pa().Table.from_pydict({k: v.tolist() if v.ndim > 1 else v
                                            for k, v in cols.items()})
        except Exception:
            n = len(next(iter(cols.values())))
            return [{k: v[i] for k, v in cols.items()} for i in range(n)]

    def num_rows(self) -> int:
        return self.block.num_rows if self._is_arrow() else len(self.block)

    def _is_arrow(self) -> bool:
        return hasattr(self.block, "column_names")

    def to_rows(self) -> List[Any]:
        if self._is_arrow():
            return self.block.to_pylist()
        return list(self.block)

    def to_batch(self) -> Dict[str, np.ndarray]:
        if self._is_arrow():
            return {name: np.asarray(self.block.column(name).to_numpy(
                zero_copy_only=False)) for name in self.block.column_names}
        if self.block and all(isinstance(r, dict) for r in self.block):
            keys = self.block[0].keys()
            return {k: np.asarray([r[k] for r in self.block]) for k in keys}
        return {"item": np.asarray(self.block, dtype=object)}

    def slice(self, start: int, end: int) -> Block:
        if self._is_arrow():
            return self.block.slice(start, end - start)
        return self.block[start:end]

    def to_pandas(self):
        if self._is_arrow():
            return self.block.to_pandas()
        import pandas as pd

        return pd.DataFrame(self.to_rows())

    def size_bytes(self) -> int:
        if self._is_arrow():
            return self.block.nbytes
        return sum(64 for _ in self.block)  # rough

"""ray_tpu.data: distributed datasets (reference: ray.data).

Lazy per-block task execution over the shared-memory object store; feeds
per-host TPU input pipelines via iter_batches / Train dataset sharding.
"""

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.datasources import (
    from_huggingface,
    read_sql,
    read_tfrecords,
    read_webdataset,
    write_tfrecords,
    write_webdataset,
)
from ray_tpu.data.dataset import (
    Dataset,
    GroupedData,
    from_blocks,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)

__all__ = [
    "Dataset",
    "GroupedData",
    "DataContext",
    "Block",
    "BlockAccessor",
    "range",
    "from_blocks",
    "from_items",
    "from_pandas",
    "from_numpy",
    "read_parquet",
    "read_csv",
    "read_json",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
    "read_sql",
    "from_huggingface",
    "write_tfrecords",
    "write_webdataset",
    "read_binary_files",
]

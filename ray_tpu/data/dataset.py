"""Dataset: lazy logical plan -> distributed block execution.

Reference: python/ray/data — ``Dataset`` (data/dataset.py) holding a logical
plan executed by a streaming executor (_internal/execution/streaming_executor
.py:66) as per-block tasks over object-store refs (RefBundle). Round-1
architecture notes:

- map-family ops chain per-block remote tasks WITHOUT barriers (each block
  streams through the whole op chain; the object store backpressures via its
  capacity + spill);
- repartition / random_shuffle / split are barrier ops;
- blocks live in the shared-memory object store; iteration pulls refs one at
  a time so only a window of blocks is resident in the driver.
"""

from __future__ import annotations

import builtins
import functools
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


# ---------------------------------------------------------------------------
# remote block transforms (execute on workers)
# ---------------------------------------------------------------------------


@ray_tpu.remote(num_cpus=1)
def _produce_block(thunk_blob: bytes) -> Block:
    thunk = cloudpickle.loads(thunk_blob)
    return thunk()


@ray_tpu.remote(num_cpus=1)
def _apply_chain(chain_blob: bytes, block: Block) -> Block:
    """Applies a list of (kind, fn) stages to one block."""
    chain = cloudpickle.loads(chain_blob)
    for kind, fn, batch_size in chain:
        acc = BlockAccessor(block)
        if kind == "map_rows":
            block = BlockAccessor.build_from_rows([fn(r) for r in acc.to_rows()])
        elif kind == "flat_map":
            out: List[Any] = []
            for r in acc.to_rows():
                out.extend(fn(r))
            block = BlockAccessor.build_from_rows(out)
        elif kind == "filter":
            block = BlockAccessor.build_from_rows(
                [r for r in acc.to_rows() if fn(r)])
        elif kind == "map_batches":
            n = acc.num_rows()
            bs = batch_size or n or 1
            outs = []
            for start in builtins.range(0, n, bs):
                batch = BlockAccessor(acc.slice(start, min(start + bs, n))).to_batch()
                result = fn(batch)
                outs.append(BlockAccessor.build_from_batch(result)
                            if isinstance(result, dict)
                            else BlockAccessor.build_from_rows(list(result)))
            rows: List[Any] = []
            for b in outs:
                rows.extend(BlockAccessor(b).to_rows())
            block = BlockAccessor.build_from_rows(rows)
        else:
            raise ValueError(kind)
    return block


@ray_tpu.remote(num_cpus=1)
def _merge_blocks(*blocks: Block) -> Block:
    rows: List[Any] = []
    for b in blocks:
        rows.extend(BlockAccessor(b).to_rows())
    return BlockAccessor.build_from_rows(rows)


@ray_tpu.remote(num_cpus=1)
def _slice_block(block: Block, start: int, end: int) -> Block:
    return BlockAccessor(block).slice(start, end)


@ray_tpu.remote(num_cpus=1)
def _count_block(block: Block) -> int:
    return BlockAccessor(block).num_rows()


@ray_tpu.remote(num_cpus=1)
def _write_parquet_block(block: Block, path: str, index: int) -> str:
    import os

    import pyarrow.parquet as pq

    acc = BlockAccessor(block)
    table = acc.block if acc._is_arrow() else None
    if table is None:
        import pyarrow as pa

        table = pa.Table.from_pylist(acc.to_rows())
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.parquet")
    pq.write_table(table, out)
    return out


# ---------------------------------------------------------------------------
# logical plan
# ---------------------------------------------------------------------------


@dataclass
class _Plan:
    # source thunks (each produces one block) OR upstream materialized refs
    source_thunks: List[bytes] = field(default_factory=list)
    source_refs: Optional[List[Any]] = None
    chain: List[tuple] = field(default_factory=list)  # (kind, fn, batch_size)
    barrier: Optional[tuple] = None  # applied after chain
    parent: Optional["_Plan"] = None


class Dataset:
    def __init__(self, plan: _Plan):
        self._plan = plan
        self._materialized: Optional[List[Any]] = None

    # -- transforms (lazy) --

    def _extend(self, stage: tuple) -> "Dataset":
        p = self._plan
        newp = _Plan(source_thunks=p.source_thunks, source_refs=p.source_refs,
                     chain=p.chain + [stage], barrier=p.barrier, parent=p.parent)
        return Dataset(newp)

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._extend(("map_rows", fn, None))

    def flat_map(self, fn: Callable[[Any], Iterable[Any]]) -> "Dataset":
        return self._extend(("flat_map", fn, None))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._extend(("filter", fn, None))

    def map_batches(self, fn: Callable[[Dict[str, np.ndarray]], Any],
                    batch_size: Optional[int] = None, **_) -> "Dataset":
        return self._extend(("map_batches", fn, batch_size))

    # -- barriers --

    def repartition(self, num_blocks: int) -> "Dataset":
        refs = self._execute()
        rows_total = sum(ray_tpu.get([_count_block.remote(r) for r in refs],
                                     timeout=600))
        merged = _merge_blocks.remote(*refs) if len(refs) > 1 else refs[0]
        per = max(1, math.ceil(rows_total / max(num_blocks, 1)))
        new_refs = [
            _slice_block.remote(merged, i * per, min((i + 1) * per, rows_total))
            for i in builtins.range(num_blocks)
            if i * per < rows_total or i == 0
        ]
        return Dataset(_Plan(source_refs=new_refs))

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        refs = self._execute()
        nblocks = max(len(refs), 1)

        def _shuffle(block, seed=seed):
            rows = BlockAccessor(block).to_rows()
            rng = np.random.default_rng(seed)
            perm = rng.permutation(len(rows))
            return BlockAccessor.build_from_rows([rows[i] for i in perm])

        merged = _merge_blocks.remote(*refs) if len(refs) > 1 else refs[0]
        shuffled = _apply_chain.remote(
            cloudpickle.dumps([("map_batches",
                                lambda b, s=seed: _shuffle_batch(b, s), None)]),
            merged)
        ds = Dataset(_Plan(source_refs=[shuffled]))
        return ds.repartition(nblocks) if nblocks > 1 else ds

    def union(self, other: "Dataset") -> "Dataset":
        return Dataset(_Plan(source_refs=self._execute() + other._execute()))

    def limit(self, n: int) -> "Dataset":
        rows = []
        for row in self.iter_rows():
            rows.append(row)
            if len(rows) >= n:
                break
        return from_items(rows, parallelism=1)

    def split(self, n: int) -> List["Dataset"]:
        """Equal row-count splits (used by Train dataset sharding)."""
        refs = self._execute()
        counts = ray_tpu.get([_count_block.remote(r) for r in refs], timeout=600)
        total = sum(counts)
        per = total // n
        merged = _merge_blocks.remote(*refs) if len(refs) > 1 else refs[0]
        out = []
        for i in builtins.range(n):
            start = i * per
            end = (i + 1) * per if i < n - 1 else total
            out.append(Dataset(_Plan(source_refs=[
                _slice_block.remote(merged, start, end)])))
        return out

    # -- execution --

    def _execute(self) -> List[Any]:
        if self._materialized is not None:
            return self._materialized
        p = self._plan
        if p.source_refs is not None:
            refs = list(p.source_refs)
        else:
            refs = [_produce_block.remote(t) for t in p.source_thunks]
        if p.chain:
            blob = cloudpickle.dumps(p.chain)
            refs = [_apply_chain.remote(blob, r) for r in refs]
        self._materialized = refs
        return refs

    def materialize(self) -> "Dataset":
        self._execute()
        return self

    # -- consumption --

    def iter_blocks(self) -> Iterator[Block]:
        for ref in self._execute():
            yield ray_tpu.get(ref, timeout=600)

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()

    def iter_batches(self, batch_size: int = 256,
                     drop_last: bool = False) -> Iterator[Dict[str, np.ndarray]]:
        carry: List[Any] = []
        for block in self.iter_blocks():
            carry.extend(BlockAccessor(block).to_rows())
            while len(carry) >= batch_size:
                chunk, carry = carry[:batch_size], carry[batch_size:]
                yield BlockAccessor(BlockAccessor.build_from_rows(chunk)).to_batch()
        if carry and not drop_last:
            yield BlockAccessor(BlockAccessor.build_from_rows(carry)).to_batch()

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        refs = self._execute()
        return sum(ray_tpu.get([_count_block.remote(r) for r in refs], timeout=600))

    def num_blocks(self) -> int:
        return len(self._execute())

    def schema(self):
        for block in self.iter_blocks():
            acc = BlockAccessor(block)
            if acc._is_arrow():
                return acc.block.schema
            rows = acc.to_rows()
            if rows:
                return type(rows[0])
        return None

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def to_pandas(self):
        import pandas as pd

        frames = [BlockAccessor(b).to_pandas() for b in self.iter_blocks()]
        return pd.concat(frames, ignore_index=True) if frames else pd.DataFrame()

    def write_parquet(self, path: str) -> List[str]:
        refs = self._execute()
        return ray_tpu.get([
            _write_parquet_block.remote(r, path, i) for i, r in enumerate(refs)
        ], timeout=600)

    def __repr__(self):
        return f"Dataset(blocks={len(self._materialized) if self._materialized else '?'})"


def _shuffle_batch(batch: Dict[str, np.ndarray], seed) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    n = len(next(iter(batch.values()))) if batch else 0
    perm = rng.permutation(n)
    return {k: np.asarray(v)[perm] for k, v in batch.items()}


# ---------------------------------------------------------------------------
# sources (reference: python/ray/data/read_api.py)
# ---------------------------------------------------------------------------


def _make_dataset(thunks: List[Callable[[], Block]]) -> Dataset:
    return Dataset(_Plan(source_thunks=[cloudpickle.dumps(t) for t in thunks]))


def range(n: int, parallelism: int = 8) -> Dataset:  # noqa: A001
    parallelism = max(1, min(parallelism, n or 1))
    per = math.ceil(n / parallelism)
    thunks = []
    for i in builtins.range(parallelism):
        start, end = i * per, min((i + 1) * per, n)
        if start >= end:
            continue
        thunks.append(functools.partial(_range_block, start, end))
    return _make_dataset(thunks)


def _range_block(start: int, end: int) -> Block:
    return BlockAccessor.build_from_rows(
        [{"id": i} for i in builtins.range(start, end)])


def from_items(items: List[Any], parallelism: int = 8) -> Dataset:
    items = list(items)
    parallelism = max(1, min(parallelism, len(items) or 1))
    per = math.ceil(len(items) / parallelism)
    thunks = []
    for i in builtins.range(parallelism):
        chunk = items[i * per:(i + 1) * per]
        if chunk:
            thunks.append(functools.partial(BlockAccessor.build_from_rows, chunk))
    return _make_dataset(thunks)


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df)
    return _make_dataset([lambda t=table: t])


def from_numpy(arr: np.ndarray) -> Dataset:
    return from_items([{"data": row} for row in arr])


def read_parquet(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, (".parquet",))
    thunks = [functools.partial(_read_parquet_file, f) for f in files]
    return _make_dataset(thunks)


def _read_parquet_file(path: str) -> Block:
    import pyarrow.parquet as pq

    return pq.read_table(path)


def read_csv(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, (".csv",))
    thunks = [functools.partial(_read_csv_file, f) for f in files]
    return _make_dataset(thunks)


def _read_csv_file(path: str) -> Block:
    from pyarrow import csv as pacsv

    return pacsv.read_csv(path)


def read_json(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, (".json", ".jsonl"))
    thunks = [functools.partial(_read_json_file, f) for f in files]
    return _make_dataset(thunks)


def _read_json_file(path: str) -> Block:
    from pyarrow import json as pajson

    return pajson.read_json(path)


def _expand_paths(paths, suffixes) -> List[str]:
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(
                os.path.join(p, f) for f in sorted(os.listdir(p))
                if f.endswith(suffixes))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no files found for {paths}")
    return files

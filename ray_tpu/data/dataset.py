"""Dataset: lazy logical plan -> streaming physical execution.

Reference: python/ray/data — ``Dataset`` (data/dataset.py) holds a logical
plan; consumption compiles it to a physical operator DAG executed by a
streaming executor thread (_internal/execution/streaming_executor.py:66)
with operator fusion (consecutive map-family stages fuse into one task per
block), actor pools for class-UDFs, two-phase hash shuffles for
sort/groupby/join/random_shuffle, bounded buffers for backpressure, and
early-stop limits. Blocks live in the shared-memory object store and move
as RefBundles; only small metadata reaches the driver.
"""

from __future__ import annotations

import builtins
import functools
import math
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.data._internal.executor import Edge, StreamingExecutor
from ray_tpu.data._internal.operators import (
    ActorPoolMapOperator,
    AllToAllOperator,
    InputDataOperator,
    LimitOperator,
    PhysicalOperator,
    ReadOperator,
    RefBundle,
    TaskPoolMapOperator,
    UnionOperator,
    WriteOperator,
    ZipOperator,
)
from ray_tpu.data._internal import tasks as T
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext

_MAP_KINDS = ("map_rows", "flat_map", "filter", "map_batches")


# ---------------------------------------------------------------------------
# logical ops
# ---------------------------------------------------------------------------


class _Op:
    """Logical plan node. kind: read | input | map-family | all2all | limit |
    union | zip | join | write."""

    __slots__ = ("kind", "args")

    def __init__(self, kind: str, **args):
        self.kind = kind
        self.args = args


class Dataset:
    def __init__(self, ops: List[_Op]):
        self._ops = ops
        self._materialized: Optional[List[RefBundle]] = None
        self._last_stats = ""

    # ------------------------------------------------------------------
    # transforms (lazy)
    # ------------------------------------------------------------------

    def _base_ops(self) -> List[_Op]:
        """Plan prefix for derived datasets: a materialized parent is reused
        as an input op so its reads/UDFs never re-execute."""
        if self._materialized is not None:
            return [_Op("input", bundles=list(self._materialized))]
        return self._ops

    def _extend(self, op: _Op) -> "Dataset":
        return Dataset(self._base_ops() + [op])

    def map(self, fn, *, num_cpus: Optional[float] = None,
            concurrency: Optional[int] = None, **kw) -> "Dataset":
        return self._map_family("map_rows", fn, None, num_cpus, concurrency, kw)

    def flat_map(self, fn, *, num_cpus: Optional[float] = None,
                 concurrency: Optional[int] = None, **kw) -> "Dataset":
        return self._map_family("flat_map", fn, None, num_cpus, concurrency, kw)

    def filter(self, fn, *, num_cpus: Optional[float] = None,
               concurrency: Optional[int] = None, **kw) -> "Dataset":
        return self._map_family("filter", fn, None, num_cpus, concurrency, kw)

    def map_batches(self, fn, *, batch_size: Optional[int] = None,
                    num_cpus: Optional[float] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    **kw) -> "Dataset":
        return self._map_family("map_batches", fn, batch_size, num_cpus,
                                concurrency,
                                dict(kw, fn_constructor_args=fn_constructor_args,
                                     fn_constructor_kwargs=fn_constructor_kwargs or {}))

    def _map_family(self, kind, fn, batch_size, num_cpus, concurrency, kw):
        is_class = isinstance(fn, type)
        return self._extend(_Op(
            "map", stage=kind, fn=fn, batch_size=batch_size,
            num_cpus=num_cpus, concurrency=concurrency, is_class=is_class,
            ctor_args=kw.get("fn_constructor_args", ()),
            ctor_kwargs=kw.get("fn_constructor_kwargs", {})))

    # -- all-to-all ----------------------------------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._extend(_Op("all2all", mode="repartition",
                                num_partitions=num_blocks))

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        return self._extend(_Op("all2all", mode="random_shuffle", seed=seed,
                                num_partitions=None))

    def sort(self, key, descending: bool = False) -> "Dataset":
        return self._extend(_Op("all2all", mode="sort", key=key,
                                descending=descending, num_partitions=None))

    def groupby(self, key) -> "GroupedData":
        return GroupedData(self, key)

    def join(self, other: "Dataset", on: str, how: str = "inner",
             num_partitions: Optional[int] = None,
             suffix: str = "_right") -> "Dataset":
        return Dataset(self._base_ops() + [
            _Op("join", right=other, on=on, how=how,
                num_partitions=num_partitions, suffix=suffix)])

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(self._base_ops() + [_Op("union", others=list(others))])

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(self._base_ops() + [_Op("zip", right=other)])

    def limit(self, n: int) -> "Dataset":
        return self._extend(_Op("limit", n=n))

    # ------------------------------------------------------------------
    # compile logical -> physical
    # ------------------------------------------------------------------

    def _compile(self, extra_op: Optional[_Op] = None
                 ) -> Tuple[List[PhysicalOperator], List[Edge], PhysicalOperator]:
        ctx = DataContext.get_current()
        ops_logical = self._ops + ([extra_op] if extra_op else [])
        phys: List[PhysicalOperator] = []
        edges: List[Edge] = []

        def link(src, dst, port="in"):
            edges.append(Edge(src, dst, port))

        def compile_into(logical: List[_Op], phys_out, edges_out):
            """Returns the tail physical op of this chain."""
            tail: Optional[PhysicalOperator] = None
            pending_maps: List[_Op] = []

            def flush_maps():
                nonlocal tail
                if not pending_maps:
                    return
                chain = []
                ctors: Dict[str, tuple] = {}
                any_class = False
                num_cpus = ctx.num_cpus_per_task
                concurrency = None
                for i, m in enumerate(pending_maps):
                    fn = m.args["fn"]
                    if m.args["is_class"]:
                        any_class = True
                        name = f"udf_{i}"
                        ctors[name] = (fn, m.args["ctor_args"], m.args["ctor_kwargs"])
                        chain.append((m.args["stage"], name, m.args["batch_size"]))
                    else:
                        chain.append((m.args["stage"], fn, m.args["batch_size"]))
                    if m.args["num_cpus"] is not None:
                        num_cpus = m.args["num_cpus"]
                    if m.args["concurrency"] is not None:
                        concurrency = m.args["concurrency"]
                label = "+".join(m.args["stage"] for m in pending_maps)
                if any_class:
                    op = ActorPoolMapOperator(
                        f"ActorMap[{label}]", chain, ctors,
                        pool_size=concurrency or 2, num_cpus=num_cpus)
                else:
                    op = TaskPoolMapOperator(
                        f"Map[{label}]", chain, num_cpus=num_cpus,
                        concurrency=concurrency)
                phys_out.append(op)
                if tail is not None:
                    link(tail, op)
                tail = op
                pending_maps.clear()

            for lop in logical:
                if lop.kind == "read":
                    op = ReadOperator(lop.args["thunks"],
                                      num_cpus=ctx.num_cpus_per_task)
                    phys_out.append(op)
                    tail = op
                elif lop.kind == "input":
                    op = InputDataOperator(lop.args["bundles"])
                    phys_out.append(op)
                    tail = op
                elif lop.kind == "map":
                    pending_maps.append(lop)
                elif lop.kind == "all2all":
                    flush_maps()
                    op = _build_all2all(lop, ctx)
                    phys_out.append(op)
                    link(tail, op)
                    tail = op
                elif lop.kind == "limit":
                    flush_maps()
                    op = LimitOperator(lop.args["n"])
                    phys_out.append(op)
                    if tail is not None:
                        link(tail, op)
                    tail = op
                elif lop.kind == "union":
                    flush_maps()
                    op = UnionOperator()
                    phys_out.append(op)
                    link(tail, op)
                    for other in lop.args["others"]:
                        other_tail = compile_into(other._ops, phys_out, edges_out)
                        link(other_tail, op)
                    tail = op
                elif lop.kind == "zip":
                    flush_maps()
                    op = ZipOperator()
                    phys_out.append(op)
                    link(tail, op, "left")
                    right_tail = compile_into(lop.args["right"]._ops,
                                              phys_out, edges_out)
                    link(right_tail, op, "right")
                    tail = op
                elif lop.kind == "join":
                    flush_maps()
                    op = _JoinOperator(lop.args["on"], lop.args["how"],
                                       lop.args["suffix"],
                                       lop.args["num_partitions"],
                                       num_cpus=ctx.num_cpus_per_task)
                    phys_out.append(op)
                    link(tail, op, "left")
                    right_tail = compile_into(lop.args["right"]._ops,
                                              phys_out, edges_out)
                    link(right_tail, op, "right")
                    tail = op
                elif lop.kind == "write":
                    flush_maps()
                    op = WriteOperator(lop.args["write_fn"],
                                       num_cpus=ctx.num_cpus_per_task)
                    phys_out.append(op)
                    link(tail, op)
                    tail = op
                else:
                    raise ValueError(lop.kind)
            flush_maps()
            assert tail is not None, "empty dataset plan"
            return tail

        tail = compile_into(ops_logical, phys, edges)
        return phys, edges, tail

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def _stream(self, extra_op: Optional[_Op] = None) -> Iterator[RefBundle]:
        if self._materialized is not None and extra_op is None:
            yield from self._materialized
            return
        phys, edges, tail = self._compile(extra_op)
        executor = StreamingExecutor(phys, edges, tail).start()
        try:
            yield from executor.iter_output()
            self._last_stats = executor.stats_summary()
            self._last_stats_data = executor.stats_data()
            self._publish_stats()
        finally:
            executor.stop()

    def materialize(self) -> "Dataset":
        if self._materialized is None:
            self._materialized = list(self._stream())
        return self

    def stats(self) -> str:
        return self._last_stats

    def stats_data(self) -> list:
        """Structured per-op metrics from the last execution (reference:
        data/_internal/stats.py DatasetStats)."""
        return getattr(self, "_last_stats_data", [])

    def _publish_stats(self):
        """Surface the last run's stats through the state API / dashboard
        (GCS KV ns="data_stats"); best-effort, skipped in local mode."""
        try:
            from ray_tpu._private import worker as worker_mod

            core = worker_mod.global_worker()
            if getattr(core, "mode", "") == "local" \
                    or not hasattr(core, "_gcs_call"):
                return
            import time as _t

            from ray_tpu._private import wire

            core._run(core._gcs_call("KVPut", {
                "ns": "data_stats", "key": self._stats_key(),
                "value": wire.dumps({"ts": _t.time(),
                                     "ops": self._last_stats_data})}),
                5.0)
        except Exception:
            pass

    def _stats_key(self) -> str:
        if not hasattr(self, "_stats_uuid"):
            import uuid as _uuid

            self._stats_uuid = _uuid.uuid4().hex[:12]
        return self._stats_uuid

    # ------------------------------------------------------------------
    # consumption
    # ------------------------------------------------------------------

    def iter_blocks(self) -> Iterator[Block]:
        for bundle in self._stream():
            block = ray_tpu.get(bundle.block, timeout=600)
            if bundle.rows is not None:
                actual = BlockAccessor(block).num_rows()
                if actual != bundle.rows:
                    raise RuntimeError(
                        f"object-plane consistency bug: block "
                        f"{bundle.block.id.hex()} produced {bundle.rows} rows "
                        f"but fetched {actual}")
            yield block

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()

    def iter_batches(self, batch_size: int = 256, drop_last: bool = False,
                     prefetch_batches: int = 1) -> Iterator[Dict[str, np.ndarray]]:
        carry: List[Any] = []
        for block in self.iter_blocks():
            carry.extend(BlockAccessor(block).to_rows())
            while len(carry) >= batch_size:
                chunk, carry = carry[:batch_size], carry[batch_size:]
                yield BlockAccessor(BlockAccessor.build_from_rows(chunk)).to_batch()
        if carry and not drop_last:
            yield BlockAccessor(BlockAccessor.build_from_rows(carry)).to_batch()

    def iter_device_batches(self, batch_size: int = 256,
                            drop_last: bool = False,
                            device_prefetch: int = 2,
                            sharding=None) -> Iterator[Any]:
        """Device-fed iteration (reference: data/iterator.py
        iter_torch_batches:106,269 — the Train ingestion path): a producer
        thread pulls host batches and starts their host->device transfer
        (``jax.device_put``, async dispatch) ``device_prefetch`` batches
        ahead, so the consumer's step compute overlaps the next batch's
        transfer instead of waiting on it. Yields pytrees of jax Arrays
        (placed per ``sharding`` when given, e.g. a data-parallel
        NamedSharding for a Train mesh)."""
        import queue as _q
        import threading as _th

        from ray_tpu.utils import import_jax

        jax = import_jax()
        depth = max(1, int(device_prefetch))
        q: _q.Queue = _q.Queue(maxsize=depth)
        stop = _th.Event()
        _END, _ERR = object(), object()

        def _put_device(batch):
            if sharding is not None:
                return jax.device_put(batch, sharding)
            return jax.device_put(batch)

        def _enqueue(item) -> bool:
            # every block is bounded so an early-exiting consumer (break
            # mid-epoch) releases this thread instead of stranding it on a
            # full queue
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.25)
                    return True
                except _q.Full:
                    continue
            return False

        def _producer():
            try:
                for batch in self.iter_batches(batch_size=batch_size,
                                               drop_last=drop_last):
                    if not _enqueue(_put_device(batch)):
                        return
                _enqueue(_END)
            except BaseException as e:  # surface in the consumer
                _enqueue((_ERR, e))

        t = _th.Thread(target=_producer, daemon=True,
                       name="ray_tpu-device-prefetch")
        t.start()
        from ray_tpu.util import goodput

        try:
            while True:
                # consumer-side queue wait IS the input stall: with the
                # prefetch pipeline keeping up this get returns
                # immediately; time spent blocked here is wall the step
                # loop lost to input
                with goodput.region("input_stall"):
                    item = q.get()
                goodput.count("input_waits")
                if item is _END:
                    break
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] is _ERR:
                    raise item[1]
                yield item
        finally:
            stop.set()
            while not q.empty():  # unblock a producer parked on put
                try:
                    q.get_nowait()
                except _q.Empty:
                    break
            t.join(timeout=2.0)

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for bundle in self.limit(n)._stream():
            block = ray_tpu.get(bundle.block, timeout=600)
            out.extend(BlockAccessor(block).to_rows())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        # row counts ride the meta stream; blocks are never fetched
        return sum(b.rows or 0 for b in self._stream())

    def num_blocks(self) -> int:
        self.materialize()
        return len(self._materialized)

    def schema(self):
        for block in self.iter_blocks():
            acc = BlockAccessor(block)
            if acc._is_arrow():
                return acc.block.schema
            rows = acc.to_rows()
            if rows:
                return type(rows[0])
        return None

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def to_pandas(self):
        import pandas as pd

        frames = [BlockAccessor(b).to_pandas() for b in self.iter_blocks()]
        return pd.concat(frames, ignore_index=True) if frames else pd.DataFrame()

    def split(self, n: int) -> List["Dataset"]:
        """Equal row-count splits (used by Train dataset sharding)."""
        self.materialize()
        bundles = self._materialized
        counts = [b.rows or 0 for b in bundles]
        total = sum(counts)
        per = total // n if n else 0
        # build row-range views over the materialized blocks
        out: List[Dataset] = []
        starts = [i * per for i in builtins.range(n)]
        ends = [(i + 1) * per if i < n - 1 else total for i in builtins.range(n)]
        for s, e in builtins.zip(starts, ends):
            refs: List[RefBundle] = []
            pos = 0
            for b, cnt in builtins.zip(bundles, counts):
                lo, hi = max(s - pos, 0), min(e - pos, cnt)
                if lo < hi:
                    if lo == 0 and hi == cnt:
                        refs.append(b)
                    else:
                        block_ref, meta_ref = T.slice_block.options(
                            num_returns=2).remote(b.block, lo, hi)
                        refs.append(RefBundle(block_ref, hi - lo, 0))
                pos += cnt
            ds = Dataset([_Op("input", bundles=refs)])
            ds._materialized = refs
            out.append(ds)
        return out

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None):
        ds: Dataset = self.random_shuffle(seed) if shuffle else self
        ds.materialize()
        total = ds.count()
        n_test = int(total * test_size)
        train, test = ds.split_at([total - n_test])
        return train, test

    def split_at(self, indices: List[int]) -> List["Dataset"]:
        self.materialize()
        bounds = [0] + list(indices) + [self.count()]
        out = []
        for s, e in builtins.zip(bounds[:-1], bounds[1:]):
            sliced = self._slice_rows(s, e)
            out.append(sliced)
        return out

    def _slice_rows(self, s: int, e: int) -> "Dataset":
        bundles = self._materialized
        refs: List[RefBundle] = []
        pos = 0
        for b in bundles:
            cnt = b.rows or 0
            lo, hi = max(s - pos, 0), min(e - pos, cnt)
            if lo < hi:
                if lo == 0 and hi == cnt:
                    refs.append(b)
                else:
                    block_ref, _ = T.slice_block.options(
                        num_returns=2).remote(b.block, lo, hi)
                    refs.append(RefBundle(block_ref, hi - lo, 0))
            pos += cnt
        ds = Dataset([_Op("input", bundles=refs)])
        ds._materialized = refs
        return ds

    # -- writes --------------------------------------------------------

    def _write(self, write_fn) -> List[str]:
        paths = []
        for bundle in self._stream(_Op("write", write_fn=write_fn)):
            block = ray_tpu.get(bundle.block, timeout=600)
            paths.extend(r["path"] for r in BlockAccessor(block).to_rows())
        return paths

    def write_parquet(self, path: str) -> List[str]:
        return self._write(functools.partial(_write_parquet_block, path))

    def write_csv(self, path: str) -> List[str]:
        return self._write(functools.partial(_write_csv_block, path))

    def write_json(self, path: str) -> List[str]:
        return self._write(functools.partial(_write_json_block, path))

    def __repr__(self):
        n = len(self._materialized) if self._materialized else "?"
        return f"Dataset(blocks={n}, ops={[o.kind for o in self._ops]})"


# ---------------------------------------------------------------------------
# groupby / aggregates
# ---------------------------------------------------------------------------


class GroupedData:
    """ds.groupby(key) -> aggregations over a hash shuffle (reference:
    grouped_data.py riding operators/hash_shuffle.py)."""

    def __init__(self, ds: Dataset, key):
        self._ds = ds
        self._key = key

    def _agg(self, spec: List[Tuple[str, Optional[str]]],
             num_partitions: Optional[int] = None) -> Dataset:
        key = self._key
        return self._ds._extend(_Op(
            "all2all", mode="groupby", key=key, agg_spec=spec,
            num_partitions=num_partitions))

    def count(self) -> Dataset:
        return self._agg([("count", None)])

    def sum(self, column: str) -> Dataset:
        return self._agg([("sum", column)])

    def mean(self, column: str) -> Dataset:
        return self._agg([("mean", column)])

    def min(self, column: str) -> Dataset:
        return self._agg([("min", column)])

    def max(self, column: str) -> Dataset:
        return self._agg([("max", column)])

    def std(self, column: str) -> Dataset:
        return self._agg([("std", column)])

    def aggregate(self, *specs: Tuple[str, Optional[str]]) -> Dataset:
        return self._agg(list(specs))

    def map_groups(self, fn) -> Dataset:
        key = self._key
        return self._ds._extend(_Op(
            "all2all", mode="map_groups", key=key, fn=fn, num_partitions=None))



def _stable_hash(value) -> int:
    """Deterministic across processes (plain hash() is salted per process,
    which would scatter equal keys across shuffle partitions)."""
    import zlib

    if isinstance(value, (int, np.integer)):
        return int(value) & 0x7FFFFFFF
    return zlib.crc32(repr(value).encode()) & 0x7FFFFFFF


def _group_reduce(rows: List[dict], key, agg_spec):
    groups: Dict[Any, List[dict]] = {}
    keyfn = key if callable(key) else (lambda r: r[key])
    for r in rows:
        groups.setdefault(keyfn(r), []).append(r)
    key_name = key if isinstance(key, str) else "key"
    out = []
    for k, members in sorted(groups.items(), key=lambda kv: repr(kv[0])):
        row = {key_name: k}
        for op, col in agg_spec:
            vals = [m[col] for m in members] if col else None
            if op == "count":
                row["count()"] = len(members)
            elif op == "sum":
                s = sum(vals)
                row[f"sum({col})"] = float(s) if isinstance(s, float) else s
            elif op == "mean":
                row[f"mean({col})"] = float(np.mean(vals))
            elif op == "min":
                row[f"min({col})"] = min(vals)  # works for any comparable
            elif op == "max":
                row[f"max({col})"] = max(vals)
            elif op == "std":
                row[f"std({col})"] = float(np.std(vals, ddof=1)) \
                    if len(vals) > 1 else 0.0
            else:
                raise ValueError(op)
        out.append(row)
    return out


def _map_groups_reduce(rows: List[dict], key, fn):
    groups: Dict[Any, List[dict]] = {}
    keyfn = key if callable(key) else (lambda r: r[key])
    for r in rows:
        groups.setdefault(keyfn(r), []).append(r)
    out = []
    for k in sorted(groups.keys(), key=repr):
        res = fn(groups[k])
        out.extend(res if isinstance(res, list) else [res])
    return out


def _build_all2all(lop: _Op, ctx: DataContext) -> AllToAllOperator:
    mode = lop.args["mode"]
    nparts = lop.args.get("num_partitions") or ctx.default_shuffle_partitions

    if mode == "repartition":
        def part_factory(_state):
            # fresh per-process entropy (pickled closures would replay the
            # same counter/rng state in every map task, skewing partitions)
            def part(row, _c={}):
                rng = _c.get("rng")
                if rng is None:
                    import random as _random

                    rng = _c["rng"] = _random.Random()
                return rng.randrange(1 << 30)
            return part

        return AllToAllOperator("Repartition", nparts, part_factory,
                                lambda _s: (lambda rows: rows),
                                num_cpus=ctx.num_cpus_per_task)

    if mode == "random_shuffle":
        seed = lop.args.get("seed")

        def part_factory(_state, seed=seed):
            if seed is not None:
                # deterministic: partition by content hash mixed with seed
                def part(row, seed=seed):
                    return _stable_hash((seed, repr(row)))
                return part

            def part(row, _c={}):
                rng = _c.get("rng")
                if rng is None:
                    import random as _random

                    rng = _c["rng"] = _random.Random()
                return rng.randrange(1 << 30)
            return part

        def reduce_factory(_state, seed=seed):
            def red(rows, seed=seed):
                rng = np.random.default_rng(seed)
                perm = rng.permutation(len(rows))
                return [rows[i] for i in perm]
            return red

        return AllToAllOperator("RandomShuffle", nparts, part_factory,
                                reduce_factory, num_cpus=ctx.num_cpus_per_task)

    if mode == "sort":
        key = lop.args["key"]
        descending = lop.args["descending"]
        keyfn = key if callable(key) else (lambda r, k=key: r[k])

        def prepare(bundles, n_parts, keyfn=keyfn):
            return T.sample_boundaries.remote(
                cloudpickle.dumps(keyfn), n_parts,
                *[b.block for b in bundles])

        def part_factory(boundaries, keyfn=keyfn):
            import bisect

            def part(row, b=boundaries, keyfn=keyfn):
                return bisect.bisect_left(b, keyfn(row)) if b else 0
            return part

        def reduce_factory(_state, keyfn=keyfn, descending=descending):
            def red(rows):
                return sorted(rows, key=keyfn, reverse=descending)
            return red

        op = AllToAllOperator("Sort", nparts, part_factory, reduce_factory,
                              prepare=prepare, num_cpus=ctx.num_cpus_per_task)
        op.ordered = True
        if descending:
            op.reverse_order = True
        return op

    if mode == "groupby":
        key = lop.args["key"]
        spec = lop.args["agg_spec"]
        keyfn = key if callable(key) else (lambda r, k=key: r[k])

        def part_factory(_state, keyfn=keyfn):
            def part(row, keyfn=keyfn):
                return _stable_hash(keyfn(row))
            return part

        def reduce_factory(_state, key=key, spec=spec):
            return functools.partial(_group_reduce, key=key, agg_spec=spec)

        return AllToAllOperator("GroupBy", nparts, part_factory,
                                reduce_factory, num_cpus=ctx.num_cpus_per_task)

    if mode == "map_groups":
        key = lop.args["key"]
        fn = lop.args["fn"]
        keyfn = key if callable(key) else (lambda r, k=key: r[k])

        def part_factory(_state, keyfn=keyfn):
            def part(row, keyfn=keyfn):
                return _stable_hash(keyfn(row))
            return part

        def reduce_factory(_state, key=key, fn=fn):
            return functools.partial(_map_groups_reduce, key=key, fn=fn)

        return AllToAllOperator("MapGroups", nparts, part_factory,
                                reduce_factory, num_cpus=ctx.num_cpus_per_task)

    raise ValueError(mode)


class _JoinOperator(AllToAllOperator):
    """Two-sided barrier: hash-partition both inputs on the key, then join
    each partition (reference: join via hash shuffle)."""

    def __init__(self, on: str, how: str, suffix: str,
                 num_partitions: Optional[int], num_cpus: float = 1.0):
        def part_factory(_state, on=on):
            def part(row, on=on):
                return _stable_hash(row.get(on))
            return part

        super().__init__(f"Join[{how} on {on}]", num_partitions, part_factory,
                         lambda _s: (lambda rows: rows), num_cpus=num_cpus)
        self._join_blob = cloudpickle.dumps((on, how, suffix))
        self._left_bundles: List[RefBundle] = []
        self._right_bundles: List[RefBundle] = []
        self._left_ids: set = set()
        self._left_outputs: List = []
        self._right_outputs: List = []
        self._left_map_count = 0

    def add_left(self, bundle: RefBundle):
        self._left_bundles.append(bundle)
        self._left_ids.add(id(bundle))
        self._input_bundles.append(bundle)

    def add_right(self, bundle: RefBundle):
        self._right_bundles.append(bundle)

    # left + right both shuffled with the same key partitioner
    def _advance_phase(self):
        if self._phase == "collect" and self.inputs_done:
            self._input_bundles = self._left_bundles + self._right_bundles
            self._start_map(None)

    def _on_map_done(self, map_ref, bundle):
        # maps finish in arbitrary order: split by side here so the reduce
        # can tell left parts from right parts
        if id(bundle) in self._left_ids:
            self._left_outputs.append(map_ref)
        else:
            self._right_outputs.append(map_ref)

    def _on_all_maps_done(self):
        self._left_map_count = len(self._left_outputs)
        self._map_outputs = self._left_outputs + self._right_outputs

    def _n_parts(self) -> int:
        if self._num_partitions:
            return self._num_partitions
        return max(1, len(self._left_bundles) + len(self._right_bundles))

    def dispatch_one(self):
        if self._map_pending:
            return super().dispatch_one()
        part_index = self._reduce_pending.popleft()
        block_ref, meta_ref = T.join_reduce.options(
            num_returns=2, num_cpus=self.num_cpus).remote(
                self._join_blob, part_index, self._left_map_count,
                *self._map_outputs)
        self._active[meta_ref] = ("reduce", block_ref, part_index)
        return [meta_ref]


# ---------------------------------------------------------------------------
# writers
# ---------------------------------------------------------------------------


def _write_parquet_block(path: str, block: Block, index: int) -> str:
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    acc = BlockAccessor(block)
    table = acc.block if acc._is_arrow() else pa.Table.from_pylist(acc.to_rows())
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.parquet")
    pq.write_table(table, out)
    return out


def _write_csv_block(path: str, block: Block, index: int) -> str:
    import os

    import pyarrow as pa
    from pyarrow import csv as pacsv

    acc = BlockAccessor(block)
    table = acc.block if acc._is_arrow() else pa.Table.from_pylist(acc.to_rows())
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.csv")
    pacsv.write_csv(table, out)
    return out


def _write_json_block(path: str, block: Block, index: int) -> str:
    import json
    import os

    acc = BlockAccessor(block)
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.jsonl")
    with open(out, "w") as f:
        for row in acc.to_rows():
            f.write(json.dumps(row, default=str) + "\n")
    return out


# ---------------------------------------------------------------------------
# sources (reference: python/ray/data/read_api.py)
# ---------------------------------------------------------------------------


def _make_dataset(thunks: List[Callable[[], Block]]) -> Dataset:
    return Dataset([_Op("read", thunks=[cloudpickle.dumps(t) for t in thunks])])


def from_blocks(blocks: List[Block]) -> Dataset:
    bundles = [RefBundle(ray_tpu.put(b), BlockAccessor(b).num_rows(),
                         BlockAccessor(b).size_bytes()) for b in blocks]
    ds = Dataset([_Op("input", bundles=bundles)])
    ds._materialized = bundles
    return ds


def range(n: int, parallelism: int = 8) -> Dataset:  # noqa: A001
    parallelism = max(1, min(parallelism, n or 1))
    per = math.ceil(n / parallelism)
    thunks = []
    for i in builtins.range(parallelism):
        start, end = i * per, min((i + 1) * per, n)
        if start >= end:
            continue
        thunks.append(functools.partial(_range_block, start, end))
    return _make_dataset(thunks)


def _range_block(start: int, end: int) -> Block:
    return BlockAccessor.build_from_rows(
        [{"id": i} for i in builtins.range(start, end)])


def from_items(items: List[Any], parallelism: int = 8) -> Dataset:
    items = list(items)
    parallelism = max(1, min(parallelism, len(items) or 1))
    per = math.ceil(len(items) / parallelism)
    thunks = []
    for i in builtins.range(parallelism):
        chunk = items[i * per:(i + 1) * per]
        if chunk:
            thunks.append(functools.partial(BlockAccessor.build_from_rows, chunk))
    return _make_dataset(thunks)


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df)
    return _make_dataset([lambda t=table: t])


def from_numpy(arr: np.ndarray) -> Dataset:
    return from_items([{"data": row} for row in arr])


def read_parquet(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, (".parquet",))
    return _make_dataset([functools.partial(_read_parquet_file, f) for f in files])


def _read_parquet_file(path: str) -> Block:
    import pyarrow.parquet as pq

    return pq.read_table(path)


def read_csv(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, (".csv",))
    return _make_dataset([functools.partial(_read_csv_file, f) for f in files])


def _read_csv_file(path: str) -> Block:
    from pyarrow import csv as pacsv

    return pacsv.read_csv(path)


def read_json(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, (".json", ".jsonl"))
    return _make_dataset([functools.partial(_read_json_file, f) for f in files])


def _read_json_file(path: str) -> Block:
    from pyarrow import json as pajson

    return pajson.read_json(path)


def read_text(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, (".txt", ".text", ".log", ""))
    return _make_dataset([functools.partial(_read_text_file, f) for f in files])


def _read_text_file(path: str) -> Block:
    with open(path, "r", errors="replace") as f:
        return BlockAccessor.build_from_rows(
            [{"text": line.rstrip("\n")} for line in f])


def read_binary_files(paths, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths, ("",))
    return _make_dataset([functools.partial(_read_binary_file, f) for f in files])


def _read_binary_file(path: str) -> Block:
    with open(path, "rb") as f:
        return BlockAccessor.build_from_rows([{"path": path, "bytes": f.read()}])


def _expand_paths(paths, suffixes) -> List[str]:
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(
                os.path.join(p, f) for f in sorted(os.listdir(p))
                if f.endswith(tuple(s for s in suffixes if s)) or "" in suffixes)
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no files found for {paths}")
    return files

"""Native token-file loader for training input pipelines.

ctypes wrapper over ``src/data_loader/loader.cc`` (built with g++ on first
use, like the arena store): a background C++ thread samples batches of
``seq+1`` consecutive tokens from an mmap'd corpus into a ring of
buffers; Python hands zero-copy int32 views to ``jax.device_put`` and
releases the slot. Falls back to a numpy memmap implementation when the
native build is unavailable (same API, same seeded sampling).

Usage::

    loader = TokenFileLoader("corpus.bin", batch=8, seq=2048, seed=0)
    for batch in loader.batches():        # {"tokens", "targets", "mask"}
        params, opt, loss = bundle.step(params, opt, device_put(batch))
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, Iterator, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "data_loader", "loader.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LIB = os.path.join(_BUILD_DIR, "libray_tpu_loader.so")

_lock = threading.Lock()
_lib = None
_typed = False


def load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _typed
    from ray_tpu._private.native_build import build_and_load

    with _lock:
        if _typed:
            return _lib
        lib = build_and_load(_SRC, _LIB, extra_flags=("-pthread",))
        _typed = True
        if lib is None:
            _lib = None
            return None
        lib.dl_create.restype = ctypes.c_void_p
        lib.dl_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_uint64,
                                  ctypes.c_int, ctypes.c_int]
        lib.dl_next.restype = ctypes.c_int
        lib.dl_next.argtypes = [ctypes.c_void_p]
        lib.dl_buffer.restype = ctypes.POINTER(ctypes.c_int32)
        lib.dl_buffer.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dl_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dl_num_tokens.restype = ctypes.c_int64
        lib.dl_num_tokens.argtypes = [ctypes.c_void_p]
        lib.dl_batches_produced.restype = ctypes.c_int64
        lib.dl_batches_produced.argtypes = [ctypes.c_void_p]
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class TokenFileLoader:
    """Double-buffered sampling loader over a binary token file."""

    def __init__(self, path: str, batch: int, seq: int, seed: int = 0,
                 n_buffers: int = 3, token_bytes: int = 4,
                 force_python: bool = False):
        self.path = path
        self.batch = batch
        self.seq = seq
        self.seed = seed
        self.token_bytes = token_bytes
        self._handle = None
        self._lib = None if force_python else load_lib()
        self.native = False
        if self._lib is not None:
            handle = self._lib.dl_create(path.encode(), batch, seq, seed or 1,
                                         n_buffers, token_bytes)
            if handle:
                self._handle = ctypes.c_void_p(handle)
                self.native = True
        if not self.native:  # pure-python fallback (same sampling scheme)
            dtype = np.uint16 if token_bytes == 2 else np.int32
            self._mm = np.memmap(path, dtype=dtype, mode="r")
            self._rng_state = np.uint64(seed or 1)

    @property
    def num_tokens(self) -> int:
        if self.native:
            return int(self._lib.dl_num_tokens(self._handle))
        return int(len(self._mm))

    def _xorshift(self) -> int:
        s = int(self._rng_state)
        s ^= (s << 13) & 0xFFFFFFFFFFFFFFFF
        s ^= s >> 7
        s ^= (s << 17) & 0xFFFFFFFFFFFFFFFF
        self._rng_state = np.uint64(s)
        return s

    def next_batch(self) -> Dict[str, np.ndarray]:
        """One {"tokens","targets","mask"} batch. The returned arrays are
        valid until the NEXT call (they view the native ring buffer) — copy
        or device_put before advancing."""
        row = self.seq + 1
        if self.native:
            # release BEFORE blocking on the next slot: with a single
            # buffer, holding it while waiting would deadlock the ring
            if getattr(self, "_held", None) is not None:
                self._lib.dl_release(self._handle, self._held)
                self._held = None
            slot = self._lib.dl_next(self._handle)
            if slot < 0:
                raise RuntimeError("loader stopped")
            self._held = slot
            ptr = self._lib.dl_buffer(self._handle, slot)
            arr = np.ctypeslib.as_array(ptr, shape=(self.batch, row))
        else:
            max_start = self.num_tokens - row
            arr = np.empty((self.batch, row), np.int32)
            for b in range(self.batch):
                start = self._xorshift() % (max_start + 1) if max_start > 0 else 0
                arr[b] = self._mm[start:start + row].astype(np.int32)
        return {
            "tokens": arr[:, :-1],
            "targets": arr[:, 1:],
            "mask": np.ones((self.batch, self.seq), np.float32),
        }

    def batches(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.next_batch()

    def batches_produced(self) -> int:
        if self.native:
            return int(self._lib.dl_batches_produced(self._handle))
        return 0

    def close(self):
        if self.native and self._handle is not None:
            if getattr(self, "_held", None) is not None:
                self._lib.dl_release(self._handle, self._held)
                self._held = None
            self._lib.dl_destroy(self._handle)
            self._handle = None
            self.native = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def write_token_file(path: str, tokens: np.ndarray, token_bytes: int = 4):
    """Helper: dump a 1-D token array in the loader's format."""
    dtype = np.uint16 if token_bytes == 2 else np.int32
    np.asarray(tokens, dtype=dtype).tofile(path)

"""ray_tpu.train: distributed training orchestration (reference: ray.train v2).

The north-star path (SURVEY.md §3.4): JaxTrainer.fit() -> TrainController
actor -> WorkerGroup gang-scheduled on a TPU slice -> jax.distributed mesh ->
user train loop with report(metrics, checkpoint) -> CheckpointManager, with
worker-group restart from the latest checkpoint on failure.
"""

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.context import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer, TrainingFailedError
from ray_tpu.train import pipeline  # lazy package: MPMD pipeline parallelism

__all__ = [
    "pipeline",
    "JaxTrainer",
    "DataParallelTrainer",
    "TrainingFailedError",
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "Result",
    "Checkpoint",
    "CheckpointManager",
    "report",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
]

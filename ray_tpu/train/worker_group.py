"""Training worker group: N actors gang-scheduled on a PG / TPU slice.

Reference: train/v2/_internal/execution/worker_group/worker_group.py:104 —
actors created in a placement group (SPREAD across hosts), each running the
user's train loop; the TPU path reserves an ICI slice first
(callbacks/tpu_reservation_callback.py:9 -> util/tpu.py slice PG).

TPU runtime ownership note (SURVEY.md §7 hard part (c)): exactly one process
per host may own the TPU, and a process that initialized jax.distributed
cannot re-form a smaller mesh — so the group always kills its workers on
shutdown/restart and re-creates fresh actor processes.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.context import TrainContext, set_context
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.util.placement_group import PlacementGroup, placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class TrainWorker:
    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._distributed = False
        self._grad_sync: Optional[Dict[str, Any]] = None

    def setup_grad_sync(self, group_name: str, backend: str,
                        bucket_bytes: int,
                        compression: Optional[str] = None) -> bool:
        """Join the group's bucketed grad-sync collective (and its
        ``.norm`` sibling for the sharded update's clip allgather + param
        broadcasts). The train loop reaches it through
        ``train.get_context().make_bucket_reducer`` /
        ``make_sharded_optimizer`` (collective/bucketed.py).
        ``compression`` (None/int8/fp8/bf16) is the default codec those
        helpers hand to the reducer/optimizer (collective/quant.py)."""
        from ray_tpu import collective as col
        from ray_tpu.collective.bucketed import init_sharded_optimizer_groups
        from ray_tpu.collective.quant import resolve_codec

        # fail at setup, not mid-train: only the CPU store-actor backend
        # implements the explicit quantized exchange (XlaGroup raises at
        # the first bucket otherwise — the XLA tier quantizes inside
        # compiled programs via TrainStepBundle(compression=...))
        if resolve_codec(compression) is not None and backend != "cpu":
            raise ValueError(
                f"grad_sync_compression={compression!r} requires "
                f"grad_sync_backend='cpu' (got {backend!r}); on-device "
                f"programs use TrainStepBundle(compression=...) instead")
        init_sharded_optimizer_groups(self.world_size, self.rank,
                                      backend=backend, base_name=group_name)
        # a group is dedicated to ONE reducer (ops match by sequence
        # number): user-level bucket reducers get their own sibling so
        # they can't interleave with a sharded optimizer's internal one
        col.init_collective_group(self.world_size, self.rank,
                                  backend=backend,
                                  group_name=f"{group_name}.user")
        self._grad_sync = {"group": group_name, "backend": backend,
                           "bucket_bytes": int(bucket_bytes),
                           "world_size": self.world_size,
                           "compression": compression}
        return True

    def get_host_info(self) -> Dict[str, Any]:
        return {
            "hostname": socket.gethostname(),
            "ip": "127.0.0.1",
            "node_id": ray_tpu.get_runtime_context().get_node_id(),
            "pid": os.getpid(),
        }

    def find_free_port(self) -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def setup_distributed(self, coordinator: str, num_processes: int,
                          process_id: int) -> bool:
        """jax.distributed bootstrap (reference: train/v2/jax/config.py:41
        _setup_jax_tpu_environment -> jax.distributed.initialize)."""
        from ray_tpu.utils import import_jax

        jax = import_jax()
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        self._distributed = True
        return True

    def run(self, fn_blob: bytes, config: Optional[dict], controller,
            latest_checkpoint_path: Optional[str], run_dir: str,
            dataset_shard_blob: Optional[bytes]) -> Dict[str, Any]:
        # driver-authored blobs: decode only through the audited
        # serialization boundary (raylint SER001)
        from ray_tpu._private.serialization import loads_trusted
        from ray_tpu.util import goodput

        # tag this process's goodput ledger with the run so its bucket
        # seconds aggregate under the right job GCS-side (a reused worker
        # switching runs resets its accumulators in set_job)
        goodput.set_job(run_dir.rsplit("/", 1)[-1])
        fn = loads_trusted(fn_blob)
        shards = loads_trusted(dataset_shard_blob) if dataset_shard_blob else {}
        ctx = TrainContext(
            rank=self.rank,
            world_size=self.world_size,
            local_rank=0,
            node_rank=self.rank,
            controller=controller,
            latest_checkpoint=(Checkpoint(latest_checkpoint_path)
                               if latest_checkpoint_path else None),
            config=config,
            dataset_shards=shards,
            grad_sync=self._grad_sync,
        )
        ctx.run_dir = run_dir
        set_context(ctx)
        try:
            if config is not None:
                result = fn(config)
            else:
                result = fn()
            return {"rank": self.rank, "result": result}
        finally:
            set_context(None)

    # -- weight plane (ray_tpu/weights/): elastic state hand-off ---------

    def publish_weight_shards(self, store_name: str, version: int,
                              shard_tree: Any, durable: bool = True) -> int:
        """Publish this rank's shard of the training state (every leaf
        sharded equally along dim 0 across the group). ``durable=True``
        routes the bytes through the store actor so the published version
        outlives this worker — the elastic re-form path: a killed group's
        surviving state is pulled back by the NEXT incarnation, resharded
        onto its (smaller) mesh, via ``pull_weight_shards``."""
        from ray_tpu.train.scaling_policy import mesh_spec_for
        from ray_tpu.util import tracing
        from ray_tpu.weights import (ShardedTreeSpec, WeightStore,
                                     publish_host_shards)
        from ray_tpu.weights.spec import flatten_tree, host_boxes
        import numpy as np

        with tracing.profile("train.publish", category="train",
                             store=store_name, version=version):
            mesh = mesh_spec_for(self.world_size)
            skeleton, leaves = flatten_tree(shard_tree)
            parts, meta, shards = {}, {}, {}
            host = mesh.hosts[self.rank]
            for path, leaf in leaves.items():
                arr = np.asarray(leaf)
                parts[path] = ("data",) + (None,) * (arr.ndim - 1)
                meta[path] = ((arr.shape[0] * self.world_size,)
                              + arr.shape[1:], arr.dtype.str)
            spec = ShardedTreeSpec(mesh=mesh, parts=parts, meta=meta)
            for path, leaf in leaves.items():
                box = host_boxes(spec.mesh, parts[path], meta[path][0],
                                 host)[0]
                shards[path] = {box: np.asarray(leaf)}
            publish_host_shards(WeightStore(store_name), version, spec, host,
                                shards, skeleton=skeleton, durable=durable)
        return version

    def pull_weight_shards(self, store_name: str,
                           version: Optional[int] = None) -> Dict[str, Any]:
        """Pull this rank's shard of the newest published state, resharded
        onto THIS group's mesh (the publisher's world size may differ —
        that is the point). Returns ``{"version": v, "tree": shard_tree}``
        with each leaf's dim 0 sized for this world."""
        from ray_tpu.train.scaling_policy import mesh_spec_for
        from ray_tpu.weights import ShardedTreeSpec, WeightStore
        from ray_tpu.weights.spec import unflatten_tree
        from ray_tpu.weights.store import _spec_from_payload

        store = WeightStore(store_name)
        man = store.manifest(version)
        src = _spec_from_payload(man["spec"])
        mesh = mesh_spec_for(self.world_size)
        dst = ShardedTreeSpec(
            mesh=mesh,
            parts={p: ("data",) + (None,) * (len(shape) - 1)
                   for p, (shape, _) in src.meta.items()},
            meta=dict(src.meta))
        shards, ver = store.pull_shards(dst, mesh.hosts[self.rank],
                                        man["version"], return_version=True)
        leaves = {p: next(iter(boxes.values())) for p, boxes in shards.items()}
        return {"version": ver, "tree": unflatten_tree(man["skeleton"], leaves)}

    def shutdown(self):
        return True


class WorkerGroup:
    def __init__(self, scaling: ScalingConfig, name_prefix: str = "train",
                 ready_timeout: float = 600.0):
        self.scaling = scaling
        self.ready_timeout = ready_timeout
        self.workers: List[Any] = []
        self.pg: Optional[PlacementGroup] = None
        self.slice_pg = None
        self._create()

    def _create(self):
        n = self.scaling.num_workers
        timeout = self.ready_timeout
        if self.scaling.use_tpu:
            from ray_tpu.util.tpu import slice_placement_group

            try:
                self.slice_pg = slice_placement_group(
                    num_hosts=n, pod_type=self.scaling.topology,
                    chips_per_host=self.scaling.chips_per_worker or None)
                if self.slice_pg.ready(timeout=timeout):
                    self.pg = self.slice_pg.placement_group
                else:
                    # unready slice reservation must be released, not
                    # silently scheduled against (leaks across retries)
                    try:
                        remove_placement_group(self.slice_pg.placement_group)
                    except Exception:
                        pass
                    self.slice_pg = None
            except Exception:
                self.pg = None  # fall through to plain PG
        if self.pg is None:
            self.pg = placement_group(
                [self.scaling.bundle() for _ in range(n)],
                strategy=self.scaling.placement_strategy
                if self.scaling.placement_strategy in
                ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD") else "SPREAD")
            if not self.pg.ready(timeout=timeout):
                from ray_tpu.exceptions import PlacementGroupError

                pg, self.pg = self.pg, None
                try:
                    remove_placement_group(pg)  # don't leak the reservation
                except Exception:
                    pass
                raise PlacementGroupError(
                    f"worker-group placement group ({n} x "
                    f"{self.scaling.bundle()}) not ready within {timeout}s")
        res = self.scaling.bundle()
        self.workers = [
            TrainWorker.options(
                num_cpus=res.get("CPU", 1.0),
                num_tpus=res.get("TPU", 0.0),
                resources={k: v for k, v in res.items() if k not in ("CPU", "TPU")},
                scheduling_strategy=PlacementGroupSchedulingStrategy(self.pg, i),
                max_restarts=0,
            ).remote(i, n)
            for i in range(n)
        ]
        # make sure every worker is alive before proceeding
        ray_tpu.get([w.get_host_info.remote() for w in self.workers],
                    timeout=self.ready_timeout)

    def setup_grad_sync(self, group_name: str, backend: str = "cpu",
                        bucket_bytes: int = 32 << 20,
                        compression: Optional[str] = None):
        """Initialize bucketed grad sync on every worker (driver side)."""
        ray_tpu.get([
            w.setup_grad_sync.remote(group_name, backend, bucket_bytes,
                                     compression)
            for w in self.workers
        ], timeout=300)

    def bootstrap_distributed(self):
        """Form the jax.distributed mesh across all workers (rank 0 hosts the
        coordinator)."""
        infos = ray_tpu.get([w.get_host_info.remote() for w in self.workers],
                            timeout=300)
        port = ray_tpu.get(self.workers[0].find_free_port.remote(), timeout=60)
        coordinator = f"{infos[0]['ip']}:{port}"
        refs = [
            w.setup_distributed.remote(coordinator, len(self.workers), i)
            for i, w in enumerate(self.workers)
        ]
        ray_tpu.get(refs, timeout=600)

    def run(self, fn_blob, config, controller, latest_ckpt, run_dir, shards_per_rank):
        return [
            w.run.remote(fn_blob, config, controller,
                         latest_ckpt.path if latest_ckpt else None, run_dir,
                         shards_per_rank[i] if shards_per_rank else None)
            for i, w in enumerate(self.workers)
        ]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None

"""Trainers: DataParallelTrainer + JaxTrainer (the north-star API).

Reference: train/v2/api/data_parallel_trainer.py:64 (``fit`` :152 spawns the
controller actor) and train/v2/jax/jax_trainer.py:19 (``JaxTrainer``).

Usage::

    def train_loop(config):
        ctx = ray_tpu.train.get_context()
        ... jax training; ray_tpu.train.report({"loss": l}, checkpoint=ckpt)

    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"steps": 100},
        scaling_config=ScalingConfig(num_workers=4, use_tpu=True),
        run_config=RunConfig(storage_path="/mnt/ckpts", name="run1"),
    )
    result = trainer.fit()
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainController


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}

    def _run_dir(self) -> str:
        base = self.run_config.storage_path or "/tmp/ray_tpu/train_runs"
        name = self.run_config.name or f"train_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:6]}"
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        return path

    def _dataset_shards(self) -> Optional[list]:
        if not self.datasets:
            return None
        n = self.scaling_config.num_workers
        per_rank: list = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "split"):
                shards = ds.split(n)
            else:
                shards = [ds] * n
            for i in range(n):
                per_rank[i][name] = shards[i]
        return [cloudpickle.dumps(d) for d in per_rank]

    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        run_dir = self._run_dir()
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        # pin the controller to the DRIVER's node (reference: the
        # controller runs alongside the driver): a controller placed on an
        # arbitrary worker node would die with it, taking down the very
        # failure handling that should survive node loss
        controller = TrainController.options(
            num_cpus=0.1, max_concurrency=8,
            name=f"train_controller_{uuid.uuid4().hex[:8]}",
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=ray_tpu.get_runtime_context().get_node_id(),
                soft=True),
        ).remote(
            cloudpickle.dumps(self.train_loop_per_worker),
            self.train_loop_config,
            self.scaling_config,
            self.run_config,
            run_dir,
            self._dataset_shards(),
        )
        ray_tpu.get(controller._set_self.remote(controller), timeout=300)
        out = ray_tpu.get(controller.run.remote(), timeout=7 * 24 * 3600)
        ray_tpu.kill(controller)
        ckpt = Checkpoint(out["checkpoint_path"]) if out.get("checkpoint_path") else None
        result = Result(metrics=out.get("metrics") or {}, checkpoint=ckpt,
                        error=out.get("error"), path=run_dir)
        if result.error:
            raise TrainingFailedError(result.error)
        return result


class TrainingFailedError(RuntimeError):
    pass


class JaxTrainer(DataParallelTrainer):
    """TPU/JAX flavor: multi-worker groups default to bootstrapping
    jax.distributed so every worker joins one SPMD mesh (reference:
    train/v2/jax/jax_trainer.py + config.py:29-41)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.scaling_config.bootstrap_distributed is None and \
                self.scaling_config.num_workers > 1:
            self.scaling_config.bootstrap_distributed = self.scaling_config.use_tpu

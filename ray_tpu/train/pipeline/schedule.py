"""1F1B pipeline schedule: per-stage op streams + an analytic simulator.

Reference: "Scaling Deep Learning Training with MPMD Pipeline Parallelism"
(PAPERS.md, arxiv 2412.14374) — the classic one-forward-one-backward
schedule (PipeDream-flush / Megatron "1F1B"): stage ``s`` of ``S`` runs
``S-1-s`` warmup forwards, then alternates one forward with one backward
until microbatches run out, then drains the remaining backwards. Peak
in-flight activations per stage are bounded by ``S-s`` (not ``M``), and
the bubble fraction is ``(S-1)/(S-1+M)`` with equal fwd/bwd-per-microbatch
costs.

Everything here is pure geometry: the schedule is a list of
:class:`Op` per stage, wire-encodable (plain tuples), golden-testable,
and executable by :mod:`ray_tpu.train.pipeline.stage` against real
channels or by :func:`simulate` against a cost model.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple

# op kinds, in the vocabulary the stage executor understands
RECV_F = "recv_f"  # read activations for microbatch mb from upstream
FWD = "fwd"        # run this stage's forward for mb (stash input for bwd)
SEND_F = "send_f"  # write mb's activations downstream
RECV_B = "recv_b"  # read mb's output-gradient from downstream
BWD = "bwd"        # run this stage's backward for mb (accumulate grads)
SEND_B = "send_b"  # write mb's input-gradient upstream

KINDS = (RECV_F, FWD, SEND_F, RECV_B, BWD, SEND_B)


class Op(NamedTuple):
    kind: str
    mb: int


def _stage_ops(stage: int, num_stages: int, num_microbatches: int
               ) -> List[Op]:
    S, M, s = num_stages, num_microbatches, stage
    first, last = s == 0, s == S - 1
    ops: List[Op] = []

    def fwd(i: int):
        if not first:
            ops.append(Op(RECV_F, i))
        ops.append(Op(FWD, i))
        if not last:
            ops.append(Op(SEND_F, i))

    def bwd(i: int):
        if not last:
            ops.append(Op(RECV_B, i))
        ops.append(Op(BWD, i))
        if not first:
            ops.append(Op(SEND_B, i))

    warmup = min(S - 1 - s, M)
    for i in range(warmup):
        fwd(i)
    for i in range(warmup, M):  # steady 1F1B
        fwd(i)
        bwd(i - warmup)
    for i in range(M - warmup, M):  # cooldown
        bwd(i)
    return ops


def build_schedule(num_stages: int, num_microbatches: int
                   ) -> List[List[Op]]:
    """Per-stage op lists for a 1F1B step. ``num_microbatches`` >= 1;
    stages with fewer microbatches than warmup slots degrade gracefully
    (pure fwd-then-bwd)."""
    if num_stages < 1 or num_microbatches < 1:
        raise ValueError(
            f"need >=1 stage and >=1 microbatch, got S={num_stages} "
            f"M={num_microbatches}")
    return [_stage_ops(s, num_stages, num_microbatches)
            for s in range(num_stages)]


def max_inflight_activations(stage: int, num_stages: int) -> int:
    """Upper bound on microbatch inputs stage ``stage`` holds at once
    under 1F1B (its warmup depth + the one in flight)."""
    return num_stages - stage


def bubble_upper_bound(num_stages: int, num_microbatches: int) -> float:
    """The analytic 1F1B bubble fraction with equal per-microbatch stage
    costs: (S-1)/(S-1+M)."""
    S, M = num_stages, num_microbatches
    return (S - 1) / float(S - 1 + M)


def simulate(num_stages: int, num_microbatches: int,
             t_fwd: float = 1.0, t_bwd: float = 2.0,
             t_comm: float = 0.0) -> Dict[str, object]:
    """Event-driven dry run of the schedule under rendezvous semantics:
    a recv waits for the matching send's completion time, sends complete
    ``t_comm`` after being posted. Returns the makespan, per-stage busy
    fractions, and the overall bubble fraction (idle compute across
    stages / total stage-time) — the number PIPE_r* reports and the
    1F1B acceptance bound checks against."""
    sched = build_schedule(num_stages, num_microbatches)
    cost = {FWD: t_fwd, BWD: t_bwd,
            RECV_F: 0.0, RECV_B: 0.0, SEND_F: t_comm, SEND_B: t_comm}
    ready: Dict[object, float] = {}  # (kind, stage, mb) -> msg-available time
    clock = [0.0] * num_stages
    busy = [0.0] * num_stages
    pos = [0] * num_stages
    remaining = sum(len(ops) for ops in sched)
    while remaining:
        progressed = False
        for s, ops in enumerate(sched):
            while pos[s] < len(ops):
                kind, mb = ops[pos[s]]
                if kind == RECV_F:
                    key = (SEND_F, s - 1, mb)
                elif kind == RECV_B:
                    key = (SEND_B, s + 1, mb)
                else:
                    key = None
                if key is not None:
                    if key not in ready:
                        break  # blocked on an unposted send; try next stage
                    clock[s] = max(clock[s], ready.pop(key))
                clock[s] += cost[kind]
                if kind in (FWD, BWD):
                    busy[s] += cost[kind]
                if kind in (SEND_F, SEND_B):
                    ready[(kind, s, mb)] = clock[s]
                pos[s] += 1
                remaining -= 1
                progressed = True
        if not progressed:
            raise RuntimeError(
                "schedule deadlocked in simulation — a recv waits on a "
                "send no stage will post (schedule generator bug)")
    makespan = max(clock)
    total_busy = sum(busy)
    bubble = 1.0 - total_busy / (makespan * num_stages) if makespan else 0.0
    return {
        "makespan": makespan,
        "busy_per_stage": busy,
        "busy_fraction_per_stage": [b / makespan if makespan else 0.0
                                    for b in busy],
        "bubble_fraction": bubble,
    }

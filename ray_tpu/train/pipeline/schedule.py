"""1F1B pipeline schedules (plain + interleaved) and an analytic simulator.

Reference: "Scaling Deep Learning Training with MPMD Pipeline Parallelism"
(PAPERS.md, arxiv 2412.14374) — the classic one-forward-one-backward
schedule (PipeDream-flush / Megatron "1F1B"): stage ``s`` of ``S`` runs
``S-1-s`` warmup forwards, then alternates one forward with one backward
until microbatches run out, then drains the remaining backwards. Peak
in-flight activations per stage are bounded by ``S-s`` (not ``M``), and
the bubble fraction is ``(S-1)/(S-1+M)`` with equal fwd/bwd-per-microbatch
costs.

Interleaved virtual stages (Megatron-LM, arxiv 2104.04473): each rank
hosts ``V`` non-contiguous model chunks, giving ``P = S*V`` virtual stages
where virtual stage ``q`` lives on rank ``q % S`` as local chunk ``q // S``.
The rank-level schedule walks *virtual microbatches* ``k`` in groups of
``S`` per chunk — forward order ``chunk(k) = (k % (S*V)) // S``,
``mb(k) = (k // (S*V)) * S + k % S`` — with warmup
``min(M*V, 2*(S-1-rank) + (V-1)*S)``. The pipeline flush shrinks by the
extra chunk turnover: bubble ``(S-1)/(S-1+V*M)`` at equal per-chunk costs.
Requires ``M % S == 0`` (the chunk rotation closes only on whole groups).

Everything here is pure geometry: the schedule is a list of
:class:`Op` per stage, wire-encodable (plain tuples), golden-testable,
and executable by :mod:`ray_tpu.train.pipeline.stage` against real
channels or by :func:`simulate` against a cost model with finite channel
depth and per-edge FIFO-order checking.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple

# op kinds, in the vocabulary the stage executor understands
RECV_F = "recv_f"  # read activations for microbatch mb from upstream
FWD = "fwd"        # run this chunk's forward for mb (stash input for bwd)
SEND_F = "send_f"  # write mb's activations downstream
RECV_B = "recv_b"  # read mb's output-gradient from downstream
BWD = "bwd"        # run this chunk's backward for mb (accumulate grads)
SEND_B = "send_b"  # write mb's input-gradient upstream

KINDS = (RECV_F, FWD, SEND_F, RECV_B, BWD, SEND_B)


class Op(NamedTuple):
    kind: str
    mb: int
    chunk: int = 0  # LOCAL chunk index on the rank (virtual stage chunk*S+rank)


def _stage_ops(stage: int, num_stages: int, num_microbatches: int
               ) -> List[Op]:
    S, M, s = num_stages, num_microbatches, stage
    first, last = s == 0, s == S - 1
    ops: List[Op] = []

    def fwd(i: int):
        if not first:
            ops.append(Op(RECV_F, i))
        ops.append(Op(FWD, i))
        if not last:
            ops.append(Op(SEND_F, i))

    def bwd(i: int):
        if not last:
            ops.append(Op(RECV_B, i))
        ops.append(Op(BWD, i))
        if not first:
            ops.append(Op(SEND_B, i))

    warmup = min(S - 1 - s, M)
    for i in range(warmup):
        fwd(i)
    for i in range(warmup, M):  # steady 1F1B
        fwd(i)
        bwd(i - warmup)
    for i in range(M - warmup, M):  # cooldown
        bwd(i)
    return ops


def build_schedule(num_stages: int, num_microbatches: int
                   ) -> List[List[Op]]:
    """Per-stage op lists for a 1F1B step. ``num_microbatches`` >= 1;
    stages with fewer microbatches than warmup slots degrade gracefully
    (pure fwd-then-bwd)."""
    if num_stages < 1 or num_microbatches < 1:
        raise ValueError(
            f"need >=1 stage and >=1 microbatch, got S={num_stages} "
            f"M={num_microbatches}")
    return [_stage_ops(s, num_stages, num_microbatches)
            for s in range(num_stages)]


def _interleaved_rank_ops(rank: int, S: int, M: int, V: int) -> List[Op]:
    total = M * V  # virtual microbatches this rank processes each direction
    P = S * V

    def fwd_ids(k: int):
        grp, pos = divmod(k, S * V)
        return pos // S, grp * S + pos % S  # (local chunk, mb)

    def bwd_ids(k: int):
        grp, pos = divmod(k, S * V)
        return V - 1 - pos // S, grp * S + pos % S

    ops: List[Op] = []

    def fwd(c: int, mb: int):
        q = c * S + rank
        if q > 0:
            ops.append(Op(RECV_F, mb, c))
        ops.append(Op(FWD, mb, c))
        if q < P - 1:
            ops.append(Op(SEND_F, mb, c))

    def bwd(c: int, mb: int):
        q = c * S + rank
        if q < P - 1:
            ops.append(Op(RECV_B, mb, c))
        ops.append(Op(BWD, mb, c))
        if q > 0:
            ops.append(Op(SEND_B, mb, c))

    # deeper warmup than plain 1F1B: (V-1)*S extra forwards keep every
    # chunk's pipeline leg full across the rotation (Megatron eq. warmup)
    warmup = min(total, 2 * (S - 1 - rank) + (V - 1) * S)
    for k in range(warmup):
        fwd(*fwd_ids(k))
    for k in range(warmup, total):  # steady 1F1B over virtual microbatches
        fwd(*fwd_ids(k))
        bwd(*bwd_ids(k - warmup))
    for k in range(total - warmup, total):  # cooldown
        bwd(*bwd_ids(k))
    return ops


def build_interleaved_schedule(num_stages: int, num_microbatches: int,
                               num_chunks: int) -> List[List[Op]]:
    """Per-RANK op lists for an interleaved 1F1B step with ``num_chunks``
    (V) model chunks per rank. ``Op.chunk`` is the rank-local chunk index;
    virtual stage = ``chunk * S + rank``. V=1 degenerates to the plain
    1F1B schedule. V>1 requires ``M % S == 0``."""
    S, M, V = num_stages, num_microbatches, num_chunks
    if V < 1:
        raise ValueError(f"need >=1 chunk per stage, got V={V}")
    if V == 1:
        return build_schedule(S, M)
    if S < 1 or M < 1:
        raise ValueError(
            f"need >=1 stage and >=1 microbatch, got S={S} M={M}")
    if M % S != 0:
        raise ValueError(
            f"interleaved schedule needs num_microbatches divisible by "
            f"num_stages (chunk rotation closes on groups of S), got "
            f"M={M} S={S}")
    return [_interleaved_rank_ops(r, S, M, V) for r in range(S)]


def max_inflight_activations(stage: int, num_stages: int,
                             num_chunks: int = 1) -> int:
    """Upper bound on microbatch inputs rank ``stage`` holds at once:
    its warmup depth + the one in flight."""
    if num_chunks == 1:
        return num_stages - stage
    return 2 * (num_stages - 1 - stage) + (num_chunks - 1) * num_stages + 1


def bubble_upper_bound(num_stages: int, num_microbatches: int,
                       num_chunks: int = 1) -> float:
    """The analytic 1F1B bubble fraction with equal per-microbatch chunk
    costs: (S-1)/(S-1+V*M) — interleaving divides the flush by V."""
    S, M, V = num_stages, num_microbatches, num_chunks
    return (S - 1) / float(S - 1 + V * M)


def simulate(num_stages: int, num_microbatches: int,
             t_fwd: float = 1.0, t_bwd: float = 2.0,
             t_comm: float = 0.0, num_chunks: int = 1,
             channel_depth: int = 0) -> Dict[str, object]:
    """Event-driven dry run of the (interleaved) schedule under the real
    channel semantics: a recv waits for the matching send's completion
    time, sends complete ``t_comm`` after being posted, and — with
    ``channel_depth`` > 0 — send #k on an edge additionally waits for the
    completion of recv #(k-depth) on that edge (ring backpressure).
    ``t_fwd``/``t_bwd`` are per-CHUNK op costs.

    Each rank pair shares one FIFO channel per direction (the executor's
    ring): the simulator asserts that every recv consumes the head of its
    channel — an out-of-order schedule raises instead of silently
    reordering, and a blocked head (or exhausted ring) with no progress
    anywhere raises a deadlock error. Returns the makespan, per-stage busy
    fractions, and the overall bubble fraction (idle compute across
    stages / total stage-time) — the number PIPE_r* reports and the
    acceptance bound checks against."""
    S, V = num_stages, num_chunks
    sched = build_interleaved_schedule(S, num_microbatches, V)
    P = S * V
    cost = {FWD: t_fwd, BWD: t_bwd,
            RECV_F: 0.0, RECV_B: 0.0, SEND_F: t_comm, SEND_B: t_comm}
    # one FIFO channel per (direction, writer rank); self-loops (S==1 wrap
    # edges) are the executor's unbounded in-memory handoff
    sends: Dict[object, list] = {}      # ch -> [((src_q, mb), ready_t), ...]
    consumed: Dict[object, int] = {}    # ch -> next unread send index
    recv_done: Dict[object, list] = {}  # ch -> completion time per recv
    clock = [0.0] * S
    busy = [0.0] * S
    pos = [0] * S
    remaining = sum(len(ops) for ops in sched)

    def _ch(kind: str, src_q: int):
        src_rank = src_q % S
        return ("f" if kind in (SEND_F, RECV_F) else "b", src_rank)

    while remaining:
        progressed = False
        for s, ops in enumerate(sched):
            while pos[s] < len(ops):
                kind, mb, c = ops[pos[s]]
                q = c * S + s
                if kind in (RECV_F, RECV_B):
                    src_q = q - 1 if kind == RECV_F else q + 1
                    ch = _ch(kind, src_q)
                    idx = consumed.get(ch, 0)
                    posted = sends.get(ch, [])
                    if idx >= len(posted):
                        break  # blocked on an unposted send; try next stage
                    key, ready_t = posted[idx]
                    if key != (src_q, mb):
                        raise RuntimeError(
                            f"channel FIFO desync on edge {ch}: rank {s} "
                            f"expects (virtual stage {src_q}, mb {mb}) but "
                            f"head of channel is {key} — schedule emits "
                            f"sends and recvs in different orders")
                    clock[s] = max(clock[s], ready_t)
                    consumed[ch] = idx + 1
                    recv_done.setdefault(ch, []).append(clock[s])
                elif kind in (SEND_F, SEND_B):
                    dst_q = q + 1 if kind == SEND_F else q - 1
                    ch = _ch(kind, q)
                    k = len(sends.setdefault(ch, []))
                    if channel_depth > 0 and dst_q % S != s:
                        done = recv_done.get(ch, [])
                        if k - channel_depth >= len(done):
                            break  # ring full: wait for a reader ack
                        if k >= channel_depth:
                            clock[s] = max(clock[s],
                                           done[k - channel_depth])
                    clock[s] += cost[kind]
                    sends[ch].append(((q, mb), clock[s]))
                    pos[s] += 1
                    remaining -= 1
                    progressed = True
                    continue
                clock[s] += cost[kind]
                if kind in (FWD, BWD):
                    busy[s] += cost[kind]
                pos[s] += 1
                remaining -= 1
                progressed = True
        if not progressed:
            raise RuntimeError(
                "schedule deadlocked in simulation — a recv waits on a "
                "send no stage will post, or every ring is full "
                "(schedule generator / channel depth bug)")
    makespan = max(clock)
    total_busy = sum(busy)
    bubble = 1.0 - total_busy / (makespan * S) if makespan else 0.0
    return {
        "makespan": makespan,
        "busy_per_stage": busy,
        "busy_fraction_per_stage": [b / makespan if makespan else 0.0
                                    for b in busy],
        "bubble_fraction": bubble,
    }

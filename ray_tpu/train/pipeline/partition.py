"""Model partitioning: the flagship transformer as S pipeline stages.

The cut is at block granularity and **name-preserving**: a stage module
re-creates exactly the parameters the full :class:`Transformer` owns under
the same top-level names (``embed``, ``layer_<i>``, ``final_norm``,
``lm_head``), so

- a stage's parameter tree is a key-subset of the full model's tree —
  :func:`split_params` / :func:`merge_params` are pure dict selection, and
  a pipeline checkpoint saved per stage can be re-partitioned onto a
  DIFFERENT stage count by reading only the leaves each new stage needs
  (no gather, no rewrite);
- ``StageModule.init`` with the full model's seed reproduces the full
  model's values for its slice (flax folds the param RNG over the module
  path, and the paths are identical).

Backward runs as stage-granularity rematerialization: FWD stashes the
microbatch *input* only, BWD re-runs the forward under ``jax.vjp`` — the
standard 1F1B memory trade (activation stash per stage is bounded by the
warmup depth, not the microbatch count; see schedule.py).

MoE aux losses compose across the cut without shipping a scalar: stage
``s``'s vjp takes cotangent ``moe_aux_coef`` on its own sown aux, and the
aux-sensitivity of *downstream* stages arrives folded into the incoming
activation gradient (the chain rule does the bookkeeping).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    lm_loss,
)
from ray_tpu.utils import import_jax


def partition_layers(n_layers: int, num_stages: int) -> List[Tuple[int, int]]:
    """Balanced contiguous [start, stop) layer ranges, earlier stages get
    the remainder (they also carry the embed table, but block cost
    dominates at depth)."""
    if not 1 <= num_stages <= n_layers:
        raise ValueError(
            f"cannot cut {n_layers} layers into {num_stages} stages")
    base, rem = divmod(n_layers, num_stages)
    out, start = [], 0
    for s in range(num_stages):
        stop = start + base + (1 if s < rem else 0)
        out.append((start, stop))
        start = stop
    return out


def stage_param_keys(cfg: TransformerConfig, stage: int, num_stages: int,
                     boundaries: Optional[List[Tuple[int, int]]] = None
                     ) -> List[str]:
    """The top-level param-dict keys stage ``stage`` owns."""
    bounds = boundaries or partition_layers(cfg.n_layers, num_stages)
    start, stop = bounds[stage]
    keys = [f"layer_{i}" for i in range(start, stop)]
    if stage == 0:
        keys.insert(0, "embed")
    if stage == num_stages - 1:
        keys.append("final_norm")
        if not cfg.tie_embeddings:
            keys.append("lm_head")
    return keys


def rank_chunk_keys(cfg: TransformerConfig, rank: int, num_stages: int,
                    num_chunks: int = 1,
                    boundaries: Optional[List[Tuple[int, int]]] = None
                    ) -> Dict[int, List[str]]:
    """Param keys per model chunk for one pipeline rank hosting
    ``num_chunks`` interleaved chunks (Megatron-style virtual stages).

    Chunk ``v`` on rank ``r`` is virtual stage ``q = v*num_stages + r``
    of the ``num_stages*num_chunks``-way cut — the interleaved placement
    is just the deeper cut re-dealt round-robin, so every key helper
    above applies unchanged at ``P = S*V``. Returns ``{q: [keys...]}``
    in local chunk order (ascending ``v``); the union across all ranks
    partitions the full key set."""
    num_virtual = num_stages * num_chunks
    return {v * num_stages + rank:
            stage_param_keys(cfg, v * num_stages + rank, num_virtual,
                             boundaries)
            for v in range(num_chunks)}


def split_params(full_params: Dict[str, Any], cfg: TransformerConfig,
                 num_stages: int,
                 boundaries: Optional[List[Tuple[int, int]]] = None
                 ) -> List[Dict[str, Any]]:
    """Cut a full model param dict into per-stage subtrees (pure key
    selection — values are shared, not copied)."""
    return [{k: full_params[k]
             for k in stage_param_keys(cfg, s, num_stages, boundaries)}
            for s in range(num_stages)]


def merge_params(stage_params: List[Dict[str, Any]]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for p in stage_params:
        out.update(p)
    return out


def _build_stage_module(cfg: TransformerConfig, start: int, stop: int,
                        first: bool, last: bool):
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Block, RMSNorm

    class StageModule(nn.Module):
        """Layers [start, stop) of the flagship transformer, plus the
        embed table (first stage) / final norm + LM head (last stage).
        Absolute layer names keep param paths identical to the full
        model's."""

        cfg: TransformerConfig

        @nn.compact
        def __call__(self, x, positions=None, segment_ids=None):
            c = self.cfg
            if first:
                tokens = x
                if positions is None:
                    positions = jnp.arange(tokens.shape[1])[None, :].astype(
                        jnp.int32)
                    positions = jnp.broadcast_to(positions, tokens.shape)
                embed = self.param(
                    "embed", nn.with_logical_partitioning(
                        nn.initializers.normal(0.02), ("vocab", "embed")),
                    (c.vocab_size, c.d_model), c.param_dtype)
                x = embed.astype(c.dtype)[tokens]
                x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
            elif positions is None:
                positions = jnp.arange(x.shape[1])[None, :].astype(jnp.int32)
                positions = jnp.broadcast_to(positions, x.shape[:2])
            block = Block
            if c.remat:
                block = nn.remat(
                    Block, prevent_cse=False,
                    policy=jax.checkpoint_policies.nothing_saveable)
            for i in range(start, stop):
                use_moe = c.n_experts > 0 and i % max(c.moe_every, 1) == 0
                x = block(c, use_moe, name=f"layer_{i}")(
                    x, positions, segment_ids)
            if not last:
                return x
            x = RMSNorm(dtype=c.dtype, name="final_norm")(x)
            if c.tie_embeddings:
                # only reachable single-stage (StagePrograms rejects tied
                # heads for S > 1), so `embed` is in scope
                logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(c.dtype))
            else:
                head = self.param(
                    "lm_head", nn.with_logical_partitioning(
                        nn.initializers.normal(0.02), ("embed", "vocab")),
                    (c.d_model, c.vocab_size), c.param_dtype)
                logits = jnp.einsum("bsd,dv->bsv", x, head.astype(c.dtype),
                                    preferred_element_type=jnp.float32)
            return nn.with_logical_constraint(logits,
                                              ("batch", "seq", "vocab"))

    return StageModule(cfg)


class StagePrograms:
    """The jitted programs one pipeline stage runs.

    - first/middle stage: ``fwd(params, x) -> (y, aux)`` and
      ``bwd(params, x, dy) -> (dparams[, dx])`` (vjp with aux cotangent
      ``moe_aux_coef``; the first stage takes no dx — tokens are ints);
    - last stage: ``bwd(params, x, targets, mask) ->
      (loss, aux, dparams, dx)`` — one value_and_grad program yields the
      step's loss AND grads (its FWD op only stashes the input; 1F1B runs
      F and B back to back on the last stage, so a separate forward would
      double its compute). ``fwd_loss`` stays as the eval entry;
    - every stage: ``acc_grads`` (microbatch accumulation),
      ``grad_sqnorm`` (for the controller's coordinated global-norm
      clip) and ``opt_apply(grads, scale, opt_state, params)``.
    """

    def __init__(self, cfg: TransformerConfig, stage: int, num_stages: int,
                 optimizer,
                 boundaries: Optional[List[Tuple[int, int]]] = None):
        if cfg.tie_embeddings and num_stages > 1:
            raise ValueError(
                "tie_embeddings shares the embed table between the first "
                "and last stage; pipeline partitioning needs untied heads")
        jax = import_jax()
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        self.stage = stage
        self.num_stages = num_stages
        self.first = stage == 0
        self.last = stage == num_stages - 1
        bounds = boundaries or partition_layers(cfg.n_layers, num_stages)
        self.start, self.stop = bounds[stage]
        self.module = _build_stage_module(cfg, self.start, self.stop,
                                          self.first, self.last)
        self.optimizer = optimizer
        coef = jnp.float32(cfg.moe_aux_coef)

        def apply_fn(params, x):
            y, cols = self.module.apply({"params": params}, x,
                                        mutable=["losses"])
            aux = sum(jax.tree.leaves(cols.get("losses", {}))) + 0.0
            return y, jnp.asarray(aux, jnp.float32)

        if self.last:
            def loss_fn(params, x, targets, mask):
                logits, aux = apply_fn(params, x)
                return lm_loss(logits, targets, mask) + coef * aux, aux

            self.fwd_loss = jax.jit(loss_fn)

            # the last stage's FWD op only stashes its input: loss AND
            # grads come from this one value_and_grad program at BWD
            # (1F1B runs them back to back — a separate forward would
            # double the most expensive stage's per-microbatch compute)
            if self.first:  # single-stage pipeline: x is int tokens
                def bwd_last(params, x, targets, mask):
                    grad_fn = jax.value_and_grad(
                        lambda p: loss_fn(p, x, targets, mask),
                        has_aux=True)
                    (loss, aux), dparams = grad_fn(params)
                    return loss, aux, dparams, None
            else:
                def bwd_last(params, x, targets, mask):
                    grad_fn = jax.value_and_grad(
                        lambda p, xx: loss_fn(p, xx, targets, mask),
                        argnums=(0, 1), has_aux=True)
                    (loss, aux), (dparams, dx) = grad_fn(params, x)
                    return loss, aux, dparams, dx

            self.bwd = jax.jit(bwd_last)
        else:
            self.fwd = jax.jit(apply_fn)
            if self.first:
                def bwd_first(params, tokens, dy):
                    _, vjp = jax.vjp(lambda p: apply_fn(p, tokens), params)
                    (dparams,) = vjp((dy, coef))
                    return dparams

                self.bwd = jax.jit(bwd_first)
            else:
                def bwd_mid(params, x, dy):
                    _, vjp = jax.vjp(apply_fn, params, x)
                    dparams, dx = vjp((dy, coef))
                    return dparams, dx

                self.bwd = jax.jit(bwd_mid)

        self.acc_grads = jax.jit(
            lambda acc, g: jax.tree.map(jnp.add, acc, g))
        self.grad_sqnorm = jax.jit(
            lambda g: sum(jnp.vdot(a.astype(jnp.float32),
                                   a.astype(jnp.float32)).real
                          for a in jax.tree.leaves(g)))

        def opt_apply(grads, scale, opt_state, params):
            grads = jax.tree.map(
                lambda a: (a.astype(jnp.float32) * scale).astype(a.dtype),
                grads)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            return optax.apply_updates(params, updates), opt_state

        self.opt_apply = jax.jit(opt_apply)

    def init(self, rng) -> Dict[str, Any]:
        """Standalone per-stage init (tests; the trainer normally places
        driver-split weights through the weight plane instead)."""
        jax = import_jax()
        import flax.linen as nn
        import jax.numpy as jnp

        c = self.cfg
        S = min(c.max_seq_len, 128)
        if self.first:
            x = jnp.zeros((1, S), dtype=jnp.int32)
        else:
            x = jnp.zeros((1, S, c.d_model), dtype=c.dtype)
        return nn.unbox(self.module.init(rng, x)["params"])

    def opt_init(self, params):
        return self.optimizer.init(params)


def make_stage_optimizer(learning_rate: float = 3e-4,
                         weight_decay: float = 0.1,
                         warmup_steps: int = 100,
                         total_steps: int = 10000,
                         b1: float = 0.9, b2: float = 0.95):
    """Per-stage optimizer matching ``parallel.train.make_optimizer``
    MINUS the global-norm clip: clipping needs the global norm across
    stages, which the pipeline controller coordinates (local sqnorms ->
    one scale for everyone) before ``opt_apply``."""
    import optax

    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay)

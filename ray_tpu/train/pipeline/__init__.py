"""ray_tpu.train.pipeline: MPMD pipeline-parallel training across actor
meshes — stage partitioning, the 1F1B schedule, stage actors streaming
microbatches over the channel plane, and the recovering controller. See
``ray_tpu/train/pipeline/README.md`` for the design.

Public surface::

    from ray_tpu.train.pipeline import (
        PipelineConfig, PipelineTrainer, build_schedule, simulate)

    trainer = PipelineTrainer(cfg, PipelineConfig(num_stages=2,
                                                  num_microbatches=8),
                              ckpt_root="/mnt/ckpts/run1")
    stats = trainer.train(num_steps=1000)
"""

# Lazy exports (PEP 562): stage/controller pull in ray_tpu actors + jax;
# schedule/partition geometry must stay importable anywhere (raylint,
# benches, the schedule golden tests) without that weight.
_EXPORTS = {
    "Op": "schedule", "build_schedule": "schedule",
    "build_interleaved_schedule": "schedule", "simulate": "schedule",
    "bubble_upper_bound": "schedule",
    "max_inflight_activations": "schedule",
    "partition_layers": "partition", "stage_param_keys": "partition",
    "rank_chunk_keys": "partition",
    "split_params": "partition", "merge_params": "partition",
    "StagePrograms": "partition", "make_stage_optimizer": "partition",
    "PipelineStage": "stage",
    "PipelineConfig": "controller", "PipelineTrainer": "controller",
    "make_microbatches": "controller",
    "repartition_manifest_leaves": "controller",
}


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module 'ray_tpu.train.pipeline' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"ray_tpu.train.pipeline.{mod}"),
                   name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


__all__ = list(_EXPORTS)

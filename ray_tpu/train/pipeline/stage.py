"""PipelineStage: one actor gang member executing a 1F1B op stream.

Each stage is an actor owning one or more chunks of the model (see
partition.py; with interleaved schedules a rank hosts ``V`` non-contiguous
chunks — virtual stage ``q = chunk*S + rank``), per-chunk optimizer state,
and the channel endpoints to its neighbor ranks. Microbatch activations
flow rank->rank over the compiled-graph channel plane (``dag/channels.py``):
a ring of shm seqlock slots on one node, the worker-mailbox push channel
across nodes — writer-creates, reader-attaches, and the depth-``d``
reader-ack backpressure keeps the no-drop rendezvous the 1F1B schedule
needs while letting a SEND overlap the next compute op. With ``V`` chunks
the physical topology is a ring: rank ``r`` writes ``f<r>`` read by rank
``(r+1)%S`` (the wrap edge carries chunk transitions) and reads ``b<r>``
written by rank ``(r+1)%S``; every hop is FIFO on its edge, and the
schedule emits sends and recvs in matching order (simulate() asserts it).

Observability: every op lands as a built-in span — ``pipe.fwd`` /
``pipe.bwd`` bound the compute, ``pipe.send`` / ``pipe.recv`` bound the
channel hops, all tagged ``stage``/``mb``/``chunk``/``step`` plus the
channel's per-hop breakdown (encode/copy/ack-wait on the send side,
copy/decode on the recv side). The sender ships its span context in the
payload and the receiver parents ``pipe.recv`` onto it, so
``/api/timeline`` renders every microbatch hand-off as a matched
cross-process flow arrow (PR 10's span plumbing, no pipeline-specific
timeline code). Aggregated channel time also lands on the
``ray_tpu.pipe.*`` metric instruments once per schedule run.

Failure model: stages are stateless between steps modulo (params,
opt_state, step) per chunk, which the controller checkpoints per stage
through the ckpt plane. A dead stage kills the step (channel reads time
out / actor death surfaces on the controller's ray.get); recovery re-forms
the whole gang at a fresh channel generation and restores every stage from
its manifest — mid-schedule partial work is discarded by construction
(grads only apply at the step boundary).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.train.pipeline import schedule as sched


def _chan_names(run: str, generation: int, stage: int, num_stages: int,
                num_chunks: int = 1) -> Dict[str, Optional[str]]:
    """Channel names for this rank's four possible endpoints. ``f<r>``
    carries rank r -> (r+1)%S activations, ``b<r>`` carries (r+1)%S -> r
    gradients; the writer side creates the ring. With one chunk per rank
    the wrap edges don't exist (plain chain); with V>1 every edge exists
    (chunk transitions ride the wrap). S==1 needs no channels at all (the
    executor hands chunks off in-process)."""
    g = f"{run}.g{generation}"
    S = num_stages
    if S == 1:
        return {"fwd_out": None, "bwd_out": None,
                "fwd_in": None, "bwd_in": None}
    if num_chunks == 1:
        return {
            "fwd_out": f"{g}.f{stage}" if stage < S - 1 else None,
            "bwd_out": f"{g}.b{stage - 1}" if stage > 0 else None,
            "fwd_in": f"{g}.f{stage - 1}" if stage > 0 else None,
            "bwd_in": f"{g}.b{stage}" if stage < S - 1 else None,
        }
    return {
        "fwd_out": f"{g}.f{stage}",
        "bwd_out": f"{g}.b{(stage - 1) % S}",
        "fwd_in": f"{g}.f{(stage - 1) % S}",
        "bwd_in": f"{g}.b{stage}",
    }


def channel_shm_paths(run: str, generation: int, num_stages: int,
                      num_chunks: int = 1) -> List[str]:
    """The /dev/shm paths a same-node gang's channels occupy (the
    controller unlinks them after killing a gang — a dead writer cannot)."""
    out = []
    for s in range(num_stages):
        names = _chan_names(run, generation, s, num_stages, num_chunks)
        for key in ("fwd_out", "bwd_out"):
            if names[key]:
                path = f"/dev/shm/rtpu_chan_{names[key]}"
                if path not in out:
                    out.append(path)
    return out


_PIPE_METRICS = None


def _pipe_metrics():
    """Lazy ``ray_tpu.pipe.*`` channel-plane instruments (one registration
    per process; recorded once per schedule run, not per hop)."""
    global _PIPE_METRICS
    if _PIPE_METRICS is None:
        from ray_tpu.util.metrics import Counter, Histogram

        _PIPE_METRICS = {
            "send_seconds": Histogram(
                "ray_tpu.pipe.send_seconds",
                description="Per-step channel send wall time on one rank "
                            "(encode + copy + downstream ack wait).",
                boundaries=[0.001, 0.01, 0.1, 1.0, 10.0],
                tag_keys=("stage",)),
            "recv_wait_seconds": Histogram(
                "ray_tpu.pipe.recv_wait_seconds",
                description="Per-step time a rank spent blocked on "
                            "upstream/downstream activations (the realized "
                            "pipeline bubble).",
                boundaries=[0.001, 0.01, 0.1, 1.0, 10.0],
                tag_keys=("stage",)),
            "wire_bytes": Counter(
                "ray_tpu.pipe.wire_bytes",
                description="Bytes written to activation channels (post-"
                            "compression framing, headers included).",
                tag_keys=("stage",)),
            "encode_seconds": Histogram(
                "ray_tpu.pipe.encode_seconds",
                description="Per-step activation framing cost on one rank "
                            "(leaf extraction + optional quantization + "
                            "skeleton pickle).",
                boundaries=[0.0001, 0.001, 0.01, 0.1, 1.0],
                tag_keys=("stage",)),
        }
    return _PIPE_METRICS


_HOP_SEND_KEYS = ("encode_s", "pickle_s", "copy_s", "ack_wait_s")
_HOP_RECV_KEYS = ("copy_s", "decode_s")


@ray_tpu.remote
class PipelineStage:
    def __init__(self, stage: int, num_stages: int, cfg_blob: bytes,
                 opt_blob: Optional[bytes], run_name: str, generation: int,
                 channel_capacity: int = 4 << 20,
                 boundaries: Optional[list] = None,
                 bucket_bytes: Optional[int] = None,
                 dp_group: Optional[Dict[str, Any]] = None,
                 num_chunks: int = 1, channel_depth: int = 2,
                 activation_compression: Optional[str] = None):
        # driver-authored blobs: decode only through the audited
        # serialization boundary (raylint SER001)
        from ray_tpu._private.serialization import loads_trusted

        self.stage = stage
        self.num_stages = num_stages
        self.num_chunks = num_chunks
        # global virtual-stage ids this rank hosts, local chunk order
        self.chunks = [v * num_stages + stage for v in range(num_chunks)]
        self.num_virtual = num_stages * num_chunks
        self.cfg = loads_trusted(cfg_blob)
        self._opt_factory = (loads_trusted(opt_blob) if opt_blob
                             else None)
        self.run_name = run_name
        self.generation = generation
        self.channel_capacity = channel_capacity
        self.channel_depth = channel_depth
        self.activation_compression = activation_compression
        self.boundaries = ([tuple(b) for b in boundaries]
                           if boundaries else None)
        # bucketed optimizer apply (None = whole-tree apply, the
        # pre-bucketing path): grads partition into size-bounded
        # layer-order buckets, each with its own optimizer state, applied
        # as a pipeline — and, with ``dp_group`` (name/world_size/rank/
        # backend of a data-parallel replica set of THIS stage), each
        # bucket's grads allreduce asynchronously across replicas as soon
        # as the schedule finishes, overlapping the controller's
        # coordination round-trip. Bucket-wise apply is bit-identical to
        # whole-tree apply for per-leaf transforms (adam family).
        self.dp_group = dict(dp_group) if dp_group else None
        if num_chunks > 1 and (bucket_bytes or dp_group):
            raise ValueError(
                "interleaved stages (num_chunks > 1) do not compose with "
                "bucket_bytes/dp_group yet — the bucket plan is keyed on a "
                "single param tree per rank")
        if self.dp_group is not None and not bucket_bytes:
            # the replica allreduce rides the bucket plan — a dp group
            # without an explicit bound gets the default bucket size
            from ray_tpu.collective.bucketed import DEFAULT_BUCKET_BYTES

            bucket_bytes = DEFAULT_BUCKET_BYTES
        self.bucket_bytes = bucket_bytes
        self._bucket_plan = None
        self._reducer = None
        self._pending_reduce: Optional[List[Any]] = None
        self.programs: Optional[Dict[int, Any]] = None  # chunk id -> programs
        self.params: Optional[Dict[int, Any]] = None    # chunk id -> tree
        self.opt_state: Optional[Dict[int, Any]] = None
        self.step = 0
        self._chans: Dict[str, Any] = {}
        self._acc: Dict[int, Any] = {}  # chunk id -> accumulated grads
        self._inputs: Dict[Tuple[int, int], Any] = {}  # (chunk, mb) -> input
        self._ibuf_f: Dict[Tuple[int, int], Any] = {}  # S==1 in-proc handoff
        self._ibuf_b: Dict[Tuple[int, int], Any] = {}
        self._last_losses: List[float] = []

    # -- single-chunk compatibility accessors -----------------------------

    def _q0(self) -> int:
        return self.chunks[0]

    def _p0(self):
        return self.params[self._q0()]

    # -- gang formation -------------------------------------------------

    def ready(self) -> bool:
        return True

    def create_channels(self) -> bool:
        """Writer side: create this rank's outgoing rings. Runs on every
        stage BEFORE any reader attaches. The forward ring optionally
        streams quantized (``activation_compression``); gradients stay
        exact."""
        from ray_tpu.dag.channels import Channel

        names = _chan_names(self.run_name, self.generation, self.stage,
                            self.num_stages, self.num_chunks)
        for key in ("fwd_out", "bwd_out"):
            if names[key] is not None:
                self._chans[key] = Channel(
                    names[key], capacity=self.channel_capacity,
                    create=True, num_readers=1, depth=self.channel_depth)
        if self.activation_compression and "fwd_out" in self._chans:
            self._chans["fwd_out"].set_codec(self.activation_compression)
        return True

    def open_channels(self, timeout: float = 30.0) -> bool:
        """Reader side: attach to the neighbors' rings (they were created
        by create_channels on every stage first; the retry only covers
        filesystem visibility)."""
        from ray_tpu.dag.channels import Channel

        names = _chan_names(self.run_name, self.generation, self.stage,
                            self.num_stages, self.num_chunks)
        deadline = time.monotonic() + timeout
        for key in ("fwd_in", "bwd_in"):
            if names[key] is None:
                continue
            while True:
                try:
                    self._chans[key] = Channel(names[key], reader_slot=0)
                    break
                except FileNotFoundError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
        return True

    def _build_programs(self):
        if self.programs is None:
            from ray_tpu.train.pipeline.partition import (
                StagePrograms, make_stage_optimizer)

            opt = (self._opt_factory() if self._opt_factory
                   else make_stage_optimizer())
            self.programs = {
                q: StagePrograms(self.cfg, q, self.num_virtual, opt,
                                 boundaries=self.boundaries)
                for q in self.chunks
            }

    def _bucketing(self):
        """Build (lazily, params must exist) the bucket plan, per-bucket
        param path sets, and — with a dp group — the async reducer.
        Single-chunk ranks only (guarded at construction)."""
        if self._bucket_plan is None and self.bucket_bytes:
            from ray_tpu.collective.bucketed import (AsyncBucketReducer,
                                                     leaf_meta, plan_buckets)

            self._bucket_plan = plan_buckets(
                leaf_meta(self._p0()), bucket_bytes=self.bucket_bytes,
                world_size=(self.dp_group or {}).get("world_size", 1))
            if self.dp_group is not None:
                from ray_tpu import collective as col

                name = f"{self.dp_group['name']}.s{self.stage}"
                col.init_collective_group(
                    self.dp_group["world_size"], self.dp_group["rank"],
                    backend=self.dp_group.get("backend", "cpu"),
                    group_name=name)
                self._reducer = AsyncBucketReducer(name, self._bucket_plan)
        return self._bucket_plan

    def _init_opt_state(self, q: int):
        """Whole-tree state, or one optimizer state per bucket (keyed by
        bucket index as str so ckpt manifests serialize it plainly)."""
        if self.bucket_bytes:
            self._bucketing()
            return {
                str(b.index): self.programs[q].opt_init(
                    self._subtree(b.paths))
                for b in self._bucket_plan.buckets
            }
        return self.programs[q].opt_init(self.params[q])

    def _flat_params(self) -> Dict[str, Any]:
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(self._p0())
        return {jax.tree_util.keystr(k): v for k, v in flat}

    def _subtree(self, paths) -> Dict[str, Any]:
        by_path = self._flat_params()
        return {p: by_path[p] for p in paths}

    def init_weights(self, store_name: str,
                     version: Optional[int] = None) -> int:
        """Pull this rank's parameter subtree from its weight-plane store
        (the per-stage weight placement path), cut it into this rank's
        chunks, and init fresh optimizer state for each."""
        from ray_tpu.train.pipeline.partition import rank_chunk_keys
        from ray_tpu.weights import WeightStore

        self._build_programs()
        tree, version = WeightStore(store_name).pull(version,
                                                     return_version=True)
        merged = tree["params"]
        self.params = {
            q: {k: merged[k] for k in keys}
            for q, keys in rank_chunk_keys(
                self.cfg, self.stage, self.num_stages, self.num_chunks,
                self.boundaries).items()
        }
        self.opt_state = {q: self._init_opt_state(q) for q in self.chunks}
        self.step = 0
        return version

    # -- schedule execution ---------------------------------------------

    def _send(self, key: str, chunk: int, mb: int, payload, step: int,
              nbytes: int, hop: Dict[str, float]):
        from ray_tpu.util import tracing

        ctx = tracing.current_context()
        span_id = tracing.new_span_id()
        trace_id = ctx[0] if ctx else tracing.new_trace_id()
        t0 = time.time()
        chan = self._chans[key]
        chan.write({"mb": mb, "chunk": chunk, "data": payload,
                    "trace": (trace_id, span_id)})
        st = chan.last_write_stats
        for k in _HOP_SEND_KEYS:
            hop["send_" + k] += st.get(k, 0.0)
        hop["send_wire_bytes"] += st.get("wire_bytes", 0)
        hop["send_skel_bytes"] += st.get("skel_bytes", 0)
        tracing.record_span(
            "pipe.send", t0, time.time(), category="pipe",
            trace_id=trace_id, span_id=span_id,
            parent_id=ctx[1] if ctx else None,
            stage=self.stage, mb=mb, chunk=chunk, step=step, nbytes=nbytes,
            wire_bytes=st.get("wire_bytes", 0),
            encode_s=st.get("encode_s", 0.0), copy_s=st.get("copy_s", 0.0),
            ack_wait_s=st.get("ack_wait_s", 0.0))

    def _recv(self, key: str, chunk: int, mb: int, step: int,
              hop: Dict[str, float]):
        from ray_tpu.util import tracing

        t0 = time.time()
        chan = self._chans[key]
        msg = chan.read()
        if msg["mb"] != mb or msg.get("chunk", 0) != chunk:
            raise RuntimeError(
                f"stage {self.stage} expected (chunk {chunk}, microbatch "
                f"{mb}) on {key}, got (chunk {msg.get('chunk')}, mb "
                f"{msg['mb']}) — schedule/channel desync")
        st = chan.last_read_stats
        for k in _HOP_RECV_KEYS:
            hop["recv_" + k] += st.get(k, 0.0)
        hop["recv_wire_bytes"] += st.get("wire_bytes", 0)
        tr = msg.get("trace")
        tracing.record_span(
            "pipe.recv", t0, time.time(), category="pipe",
            trace_id=tr[0] if tr else tracing.new_trace_id(),
            span_id=tracing.new_span_id(),
            parent_id=tr[1] if tr else None,
            stage=self.stage, mb=mb, chunk=chunk, step=step,
            copy_s=st.get("copy_s", 0.0), decode_s=st.get("decode_s", 0.0))
        return msg["data"]

    def run_schedule(self, step: int, ops: List, microbatches: Optional[List[dict]] = None) -> Dict[str, Any]:
        """Execute one step's op stream (``schedule.build_schedule`` /
        ``build_interleaved_schedule`` row for this rank; op tuples are
        ``(kind, mb[, chunk])``). ``microbatches`` carries the
        per-microbatch host data this rank consumes: ``tokens`` on the
        rank hosting virtual stage 0, ``targets``/``mask`` on the rank
        hosting the last."""
        from ray_tpu.util import tracing

        self._build_programs()
        if self.params is None:
            raise RuntimeError(
                f"stage {self.stage}: init_weights/restore before running")
        jax = _jax()
        S, P = self.num_stages, self.num_virtual
        wall0 = time.perf_counter()
        compute_s = send_s = recv_s = 0.0
        send_bytes = recv_bytes = 0
        hop = {("send_" + k): 0.0 for k in _HOP_SEND_KEYS}
        hop.update({("recv_" + k): 0.0 for k in _HOP_RECV_KEYS})
        hop["send_wire_bytes"] = 0
        hop["send_skel_bytes"] = 0
        hop["recv_wire_bytes"] = 0
        losses: List[float] = []
        auxes: List[float] = []
        aux_by_mb: Dict[int, float] = {}
        self._acc = {}
        self._inputs.clear()
        self._ibuf_f.clear()
        self._ibuf_b.clear()
        for op in ops:
            kind, mb = op[0], op[1]
            c = op[2] if len(op) > 2 else 0
            q = c * S + self.stage  # global virtual stage
            p = self.programs[q]
            if kind == sched.RECV_F:
                t0 = time.perf_counter()
                if S == 1:
                    x = self._ibuf_f.pop((c, mb))
                else:
                    x = self._recv("fwd_in", c, mb, step, hop)
                recv_s += time.perf_counter() - t0
                recv_bytes += x.nbytes
                self._inputs[(c, mb)] = x
            elif kind == sched.FWD:
                if p.first:
                    self._inputs[(c, mb)] = microbatches[mb]["tokens"]
                x = self._inputs[(c, mb)]
                t0 = time.perf_counter()
                with tracing.profile("pipe.fwd", category="pipe",
                                     stage=self.stage, mb=mb, chunk=c,
                                     step=step):
                    if p.last:
                        # stash only: the BWD value_and_grad computes the
                        # loss — F and B are adjacent here, a separate
                        # forward would double this stage's compute
                        self._y = None
                    else:
                        y, aux = p.fwd(self.params[q], x)
                        jax.block_until_ready(y)
                        auxes.append(float(aux))
                        aux_by_mb[mb] = aux_by_mb.get(mb, 0.0) + float(aux)
                        self._y = np.asarray(y)
                compute_s += time.perf_counter() - t0
            elif kind == sched.SEND_F:
                t0 = time.perf_counter()
                if S == 1:
                    self._ibuf_f[((q + 1) // S, mb)] = self._y
                else:
                    self._send("fwd_out", (q + 1) // S, mb, self._y, step,
                               self._y.nbytes, hop)
                send_bytes += self._y.nbytes
                self._y = None
                send_s += time.perf_counter() - t0
            elif kind == sched.RECV_B:
                t0 = time.perf_counter()
                if S == 1:
                    dy = self._ibuf_b.pop((c, mb))
                else:
                    dy = self._recv("bwd_in", c, mb, step, hop)
                recv_s += time.perf_counter() - t0
                recv_bytes += dy.nbytes
                self._dy = dy
            elif kind == sched.BWD:
                x = self._inputs.pop((c, mb))
                t0 = time.perf_counter()
                with tracing.profile("pipe.bwd", category="pipe",
                                     stage=self.stage, mb=mb, chunk=c,
                                     step=step):
                    if p.last:
                        loss, aux, dparams, dx = p.bwd(
                            self.params[q], x, microbatches[mb]["targets"],
                            microbatches[mb]["mask"])
                        losses.append(float(loss))
                        auxes.append(float(aux))
                        # NOT folded into aux_by_mb: the last virtual
                        # stage's aux is already inside its loss
                    elif p.first:
                        dparams, dx = p.bwd(self.params[q], x,
                                            self._dy), None
                        self._dy = None
                    else:
                        dparams, dx = p.bwd(self.params[q], x, self._dy)
                        self._dy = None
                    acc = self._acc.get(q)
                    self._acc[q] = (dparams if acc is None
                                    else p.acc_grads(acc, dparams))
                    jax.block_until_ready(self._acc[q])
                    self._dx = None if dx is None else np.asarray(dx)
                compute_s += time.perf_counter() - t0
            elif kind == sched.SEND_B:
                t0 = time.perf_counter()
                if S == 1:
                    self._ibuf_b[((q - 1) // S, mb)] = self._dx
                else:
                    self._send("bwd_out", (q - 1) // S, mb, self._dx, step,
                               self._dx.nbytes, hop)
                send_bytes += self._dx.nbytes
                self._dx = None
                send_s += time.perf_counter() - t0
            else:
                raise ValueError(f"unknown schedule op {kind!r}")
        self._last_losses = losses
        reduce_launched = False
        if self.dp_group is not None and self._acc.get(self._q0()) is not None:
            # kick every bucket's cross-replica allreduce NOW, async: the
            # collectives run while the controller is still collecting
            # results and coordinating the clip across stages
            self._launch_reduce()
            reduce_launched = True
        # goodput attribution from the per-op timers this schedule already
        # keeps: recv waits are the pipeline bubble (idle until a neighbor
        # produces), send waits block on channel backpressure
        from ray_tpu.util import goodput

        goodput.set_job(self.run_name)
        goodput.add("step_compute", compute_s)
        goodput.add("bubble", recv_s)
        goodput.add("collective_wait", send_s)
        goodput.count("steps")
        m = _pipe_metrics()
        tags = {"stage": str(self.stage)}
        m["send_seconds"].observe(send_s, tags=tags)
        m["recv_wait_seconds"].observe(recv_s, tags=tags)
        m["encode_seconds"].observe(
            hop["send_encode_s"] + hop["send_pickle_s"], tags=tags)
        if hop["send_wire_bytes"]:
            m["wire_bytes"].inc(hop["send_wire_bytes"], tags=tags)
        return {
            "stage": self.stage,
            "losses": losses,
            "aux": auxes,
            "aux_by_mb": aux_by_mb,
            "wall_s": time.perf_counter() - wall0,
            "compute_s": compute_s,
            "send_s": send_s,
            "recv_wait_s": recv_s,
            "send_bytes": send_bytes,
            "recv_bytes": recv_bytes,
            "hop": hop,
            "reduce_launched": reduce_launched,
        }

    def _launch_reduce(self):
        """Submit every bucket's grad allreduce to the async reducer (one
        ``train.bucket_allreduce`` span per bucket lands as each
        completes)."""
        import jax

        self._bucketing()
        flat, _ = jax.tree_util.tree_flatten_with_path(
            self._acc[self._q0()])
        by_path = {jax.tree_util.keystr(k): np.asarray(v) for k, v in flat}
        self._pending_reduce = [
            self._reducer.submit(b, {p: by_path[p] for p in b.paths})
            for b in self._bucket_plan.buckets
        ]

    def _collect_reduced(self):
        """Fold completed bucket allreduces back into the accumulated
        grad tree (idempotent; no-op without a dp group)."""
        if self._pending_reduce is None:
            return
        import jax

        from ray_tpu.util import goodput

        q0 = self._q0()
        flat, treedef = jax.tree_util.tree_flatten_with_path(self._acc[q0])
        paths = [jax.tree_util.keystr(k) for k, _ in flat]
        reduced: Dict[str, np.ndarray] = {}
        with goodput.region("collective_wait"):
            for handle in self._pending_reduce:
                reduced.update(handle.result())
        self._pending_reduce = None
        self._acc[q0] = jax.tree_util.tree_unflatten(
            treedef, [reduced[p] for p in paths])

    # -- step boundary ---------------------------------------------------

    def grad_sqnorm(self) -> float:
        if not self._acc:
            raise RuntimeError(f"stage {self.stage}: no accumulated grads")
        self._collect_reduced()  # clip must see the cross-replica sum
        return float(sum(float(self.programs[q].grad_sqnorm(g))
                         for q, g in self._acc.items()))

    def apply_grads(self, scale: float) -> int:
        """Scale the accumulated grads (1/M and the coordinated global
        clip, folded into one factor by the controller) and step each
        chunk's optimizer. With ``bucket_bytes`` set the update applies
        bucket by bucket (per-bucket optimizer state, ``pipe.bucket_apply``
        spans) — bit-identical to the whole-tree apply for per-leaf
        transforms."""
        if not self._acc:
            raise RuntimeError(f"stage {self.stage}: no accumulated grads")
        self._collect_reduced()
        if not self.bucket_bytes:
            for q in self.chunks:
                self.params[q], self.opt_state[q] = \
                    self.programs[q].opt_apply(self._acc[q], scale,
                                               self.opt_state[q],
                                               self.params[q])
            self._acc = {}
            self.step += 1
            return self.step
        import jax

        from ray_tpu.util import tracing

        q0 = self._q0()
        self._bucketing()
        flat, treedef = jax.tree_util.tree_flatten_with_path(self.params[q0])
        paths = [jax.tree_util.keystr(k) for k, _ in flat]
        by_path = dict(zip(paths, (v for _, v in flat)))
        gflat, _ = jax.tree_util.tree_flatten_with_path(self._acc[q0])
        g_by_path = {jax.tree_util.keystr(k): v for k, v in gflat}
        for b in self._bucket_plan.buckets:
            with tracing.profile("pipe.bucket_apply", category="pipe",
                                 stage=self.stage, bucket=b.index,
                                 nbytes=b.nbytes, step=self.step):
                p_sub = {p: by_path[p] for p in b.paths}
                g_sub = {p: g_by_path[p] for p in b.paths}
                new_sub, self.opt_state[q0][str(b.index)] = \
                    self.programs[q0].opt_apply(
                        g_sub, scale, self.opt_state[q0][str(b.index)],
                        p_sub)
                by_path.update(new_sub)
        self.params[q0] = jax.tree_util.tree_unflatten(
            treedef, [by_path[p] for p in paths])
        self._acc = {}
        self.step += 1
        return self.step

    # -- ckpt plane -------------------------------------------------------

    def save_ckpt(self, ckpt_root: str, step: int) -> str:
        """Per-stage checkpoint through the ckpt plane: one manifest over
        content-addressed chunks per stage, spec-tagged with this stage's
        geometry so a restore onto a different gang shape reshards
        no-gather (ckpt.restore_shards). Single-chunk ranks keep the
        pre-interleaving layout (params/opt_state at the top level);
        multi-chunk ranks nest per virtual stage under ``chunks``."""
        from ray_tpu import ckpt
        from ray_tpu.weights.spec import MeshSpec, ShardedTreeSpec

        if self.num_chunks == 1:
            q0 = self._q0()
            tree = {"params": self.params[q0],
                    "opt_state": self.opt_state[q0],
                    "step": np.int64(step)}
        else:
            tree = {"chunks": {str(q): {"params": self.params[q],
                                        "opt_state": self.opt_state[q]}
                               for q in self.chunks},
                    "step": np.int64(step)}
        spec = ShardedTreeSpec.from_tree(
            tree, MeshSpec.host_mesh([f"stage{self.stage}"]))
        store = ckpt.CheckpointStore(
            os.path.join(ckpt_root, f"stage{self.stage}"),
            name=f"{self.run_name}-s{self.stage}")
        man = ckpt.save_checkpoint(store, tree, step=step, spec=spec)
        return man.ckpt_id

    def restore_ckpt(self, ckpt_root: str,
                     target_step: Optional[int] = None) -> Optional[int]:
        """Restore per-chunk (params, opt_state) + step from this stage's
        latest manifest — or, with ``target_step``, the newest manifest at
        or below it (the controller's rollback when a crash mid-save left
        stages disagreeing). None when no usable checkpoint exists (the
        caller falls back to weight-plane init)."""
        from ray_tpu import ckpt

        self._build_programs()
        store = ckpt.CheckpointStore(
            os.path.join(ckpt_root, f"stage{self.stage}"),
            name=f"{self.run_name}-s{self.stage}")
        manifest = store.latest()
        if target_step is not None:
            cands = [m for m in store.list() if m.step <= target_step]
            manifest = max(cands, key=lambda m: m.step) if cands else None
        if manifest is None:
            return None
        tree = ckpt.restore_tree(store, manifest.ckpt_id)
        if "chunks" in tree:
            saved = set(tree["chunks"])
            expect = {str(q) for q in self.chunks}
            if saved != expect:
                raise RuntimeError(
                    f"stage {self.stage}: checkpoint holds chunks "
                    f"{sorted(saved)} but this rank hosts {sorted(expect)} "
                    f"— restore with the run's original num_chunks")
            self.params = {q: tree["chunks"][str(q)]["params"]
                           for q in self.chunks}
            self.opt_state = {q: tree["chunks"][str(q)]["opt_state"]
                              for q in self.chunks}
            self.step = int(tree["step"])
            return self.step
        if self.num_chunks > 1:
            raise RuntimeError(
                f"stage {self.stage}: checkpoint is single-chunk but this "
                f"rank hosts {self.num_chunks} chunks — restore with the "
                f"run's original num_chunks")
        restored = tree["opt_state"]
        # bucketed opt state serializes as {bucket_index_str: state}; a
        # mode/bucket_bytes change between save and restore cannot be
        # silently adopted (apply_grads would index the wrong shape)
        was_bucketed = isinstance(restored, dict) and all(
            isinstance(k, str) and k.isdigit() for k in restored)
        if bool(self.bucket_bytes) != was_bucketed:
            raise RuntimeError(
                f"stage {self.stage}: checkpoint opt state is "
                f"{'bucketed' if was_bucketed else 'whole-tree'} but this "
                f"stage is configured {'bucketed' if self.bucket_bytes else 'whole-tree'} "
                f"— restore with the run's original bucket_bytes setting")
        q0 = self._q0()
        self.params = {q0: tree["params"]}
        if was_bucketed:
            plan = self._bucketing()
            expect = {str(b.index) for b in plan.buckets}
            if set(restored) != expect:
                raise RuntimeError(
                    f"stage {self.stage}: checkpoint has buckets "
                    f"{sorted(restored)} but the current plan has "
                    f"{sorted(expect)} — bucket_bytes changed between "
                    f"save and restore")
        self.opt_state = {q0: restored}
        self.step = int(tree["step"])
        return self.step

    # -- introspection / teardown ----------------------------------------

    def state_digest(self) -> Dict[str, float]:
        """Cheap content fingerprint for tests (param/opt sums + step)."""
        jax = _jax()

        psum = float(sum(
            np.asarray(a, dtype=np.float64).sum()
            for q in self.chunks
            for a in jax.tree.leaves(self.params[q])))
        return {"step": self.step, "param_sum": psum}

    def pull_params(self) -> Dict[str, Any]:
        """This rank's param subtree (all chunks merged — top-level keys
        partition disjointly) as host arrays (tests, small models;
        production consumers go through the weight plane)."""
        jax = _jax()

        out: Dict[str, Any] = {}
        for q in self.chunks:
            out.update(jax.tree.map(lambda a: np.asarray(a),
                                    self.params[q]))
        return out

    def close_channels(self, unlink: bool = False) -> bool:
        for chan in self._chans.values():
            try:
                chan.close(unlink=unlink)
            except Exception:
                pass
        self._chans.clear()
        return True

    def shutdown(self) -> bool:
        if self._reducer is not None:
            try:
                self._reducer.shutdown()
            except Exception:
                pass
            self._reducer = None
        self.close_channels(unlink=True)
        return True


def _jax():
    from ray_tpu.utils import import_jax

    return import_jax()

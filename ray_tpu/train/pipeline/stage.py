"""PipelineStage: one actor gang member executing a 1F1B op stream.

Each stage is an actor owning one partition of the model (see
partition.py), its own optimizer state, and the channel endpoints to its
neighbors. Microbatch activations flow stage->stage over the compiled-graph
channel plane (``dag/channels.py``): the shm seqlock slot on one node, the
worker-mailbox push channel across nodes — writer-creates, reader-attaches,
and the depth-1 reader-ack backpressure is exactly the rendezvous the 1F1B
schedule needs (a stage can run at most one send ahead of its consumer).

Observability: every op lands as a built-in span — ``pipe.fwd`` /
``pipe.bwd`` bound the compute, ``pipe.send`` / ``pipe.recv`` bound the
channel hops, all tagged ``stage``/``mb``/``step``. The sender ships its
span context in the payload and the receiver parents ``pipe.recv`` onto
it, so ``/api/timeline`` renders every microbatch hand-off as a matched
cross-process flow arrow (PR 10's span plumbing, no pipeline-specific
timeline code).

Failure model: stages are stateless between steps modulo (params,
opt_state, step), which the controller checkpoints per stage through the
ckpt plane. A dead stage kills the step (channel reads time out / actor
death surfaces on the controller's ray.get); recovery re-forms the whole
gang at a fresh channel generation and restores every stage from its
manifest — mid-schedule partial work is discarded by construction (grads
only apply at the step boundary).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.train.pipeline import schedule as sched


def _chan_names(run: str, generation: int, stage: int, num_stages: int
                ) -> Dict[str, Optional[str]]:
    """Channel names for this stage's four possible endpoints. ``f<s>``
    carries stage s -> s+1 activations, ``b<s>`` carries s+1 -> s
    gradients; the writer side creates the slot."""
    g = f"{run}.g{generation}"
    return {
        "fwd_out": f"{g}.f{stage}" if stage < num_stages - 1 else None,
        "bwd_out": f"{g}.b{stage - 1}" if stage > 0 else None,
        "fwd_in": f"{g}.f{stage - 1}" if stage > 0 else None,
        "bwd_in": f"{g}.b{stage}" if stage < num_stages - 1 else None,
    }


def channel_shm_paths(run: str, generation: int, num_stages: int
                      ) -> List[str]:
    """The /dev/shm paths a same-node gang's channels occupy (the
    controller unlinks them after killing a gang — a dead writer cannot)."""
    out = []
    for s in range(num_stages):
        names = _chan_names(run, generation, s, num_stages)
        for key in ("fwd_out", "bwd_out"):
            if names[key]:
                out.append(f"/dev/shm/rtpu_chan_{names[key]}")
    return out


@ray_tpu.remote
class PipelineStage:
    def __init__(self, stage: int, num_stages: int, cfg_blob: bytes,
                 opt_blob: Optional[bytes], run_name: str, generation: int,
                 channel_capacity: int = 4 << 20,
                 boundaries: Optional[list] = None,
                 bucket_bytes: Optional[int] = None,
                 dp_group: Optional[Dict[str, Any]] = None):
        # driver-authored blobs: decode only through the audited
        # serialization boundary (raylint SER001)
        from ray_tpu._private.serialization import loads_trusted

        self.stage = stage
        self.num_stages = num_stages
        self.cfg = loads_trusted(cfg_blob)
        self._opt_factory = (loads_trusted(opt_blob) if opt_blob
                             else None)
        self.run_name = run_name
        self.generation = generation
        self.channel_capacity = channel_capacity
        self.boundaries = ([tuple(b) for b in boundaries]
                           if boundaries else None)
        # bucketed optimizer apply (None = whole-tree apply, the
        # pre-bucketing path): grads partition into size-bounded
        # layer-order buckets, each with its own optimizer state, applied
        # as a pipeline — and, with ``dp_group`` (name/world_size/rank/
        # backend of a data-parallel replica set of THIS stage), each
        # bucket's grads allreduce asynchronously across replicas as soon
        # as the schedule finishes, overlapping the controller's
        # coordination round-trip. Bucket-wise apply is bit-identical to
        # whole-tree apply for per-leaf transforms (adam family).
        self.dp_group = dict(dp_group) if dp_group else None
        if self.dp_group is not None and not bucket_bytes:
            # the replica allreduce rides the bucket plan — a dp group
            # without an explicit bound gets the default bucket size
            from ray_tpu.collective.bucketed import DEFAULT_BUCKET_BYTES

            bucket_bytes = DEFAULT_BUCKET_BYTES
        self.bucket_bytes = bucket_bytes
        self._bucket_plan = None
        self._reducer = None
        self._pending_reduce: Optional[List[Any]] = None
        self.programs = None
        self.params = None
        self.opt_state = None
        self.step = 0
        self._chans: Dict[str, Any] = {}
        self._acc = None  # accumulated grads across a step's microbatches
        self._inputs: Dict[int, Any] = {}  # mb -> stashed fwd input
        self._last_losses: List[float] = []

    # -- gang formation -------------------------------------------------

    def ready(self) -> bool:
        return True

    def create_channels(self) -> bool:
        """Writer side: create this stage's outgoing slots. Runs on every
        stage BEFORE any reader attaches."""
        from ray_tpu.dag.channels import Channel

        names = _chan_names(self.run_name, self.generation, self.stage,
                            self.num_stages)
        for key in ("fwd_out", "bwd_out"):
            if names[key] is not None:
                self._chans[key] = Channel(
                    names[key], capacity=self.channel_capacity,
                    create=True, num_readers=1)
        return True

    def open_channels(self, timeout: float = 30.0) -> bool:
        """Reader side: attach to the neighbors' slots (they were created
        by create_channels on every stage first; the retry only covers
        filesystem visibility)."""
        from ray_tpu.dag.channels import Channel

        names = _chan_names(self.run_name, self.generation, self.stage,
                            self.num_stages)
        deadline = time.monotonic() + timeout
        for key in ("fwd_in", "bwd_in"):
            if names[key] is None:
                continue
            while True:
                try:
                    self._chans[key] = Channel(names[key], reader_slot=0)
                    break
                except FileNotFoundError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
        return True

    def _build_programs(self):
        if self.programs is None:
            from ray_tpu.train.pipeline.partition import (
                StagePrograms, make_stage_optimizer)

            opt = (self._opt_factory() if self._opt_factory
                   else make_stage_optimizer())
            self.programs = StagePrograms(
                self.cfg, self.stage, self.num_stages, opt,
                boundaries=self.boundaries)

    def _bucketing(self):
        """Build (lazily, params must exist) the bucket plan, per-bucket
        param path sets, and — with a dp group — the async reducer."""
        if self._bucket_plan is None and self.bucket_bytes:
            from ray_tpu.collective.bucketed import (AsyncBucketReducer,
                                                     leaf_meta, plan_buckets)

            self._bucket_plan = plan_buckets(
                leaf_meta(self.params), bucket_bytes=self.bucket_bytes,
                world_size=(self.dp_group or {}).get("world_size", 1))
            if self.dp_group is not None:
                from ray_tpu import collective as col

                name = f"{self.dp_group['name']}.s{self.stage}"
                col.init_collective_group(
                    self.dp_group["world_size"], self.dp_group["rank"],
                    backend=self.dp_group.get("backend", "cpu"),
                    group_name=name)
                self._reducer = AsyncBucketReducer(name, self._bucket_plan)
        return self._bucket_plan

    def _init_opt_state(self):
        """Whole-tree state, or one optimizer state per bucket (keyed by
        bucket index as str so ckpt manifests serialize it plainly)."""
        if self.bucket_bytes:
            self._bucketing()
            return {
                str(b.index): self.programs.opt_init(
                    self._subtree(b.paths))
                for b in self._bucket_plan.buckets
            }
        return self.programs.opt_init(self.params)

    def _flat_params(self) -> Dict[str, Any]:
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(self.params)
        return {jax.tree_util.keystr(k): v for k, v in flat}

    def _subtree(self, paths) -> Dict[str, Any]:
        by_path = self._flat_params()
        return {p: by_path[p] for p in paths}

    def init_weights(self, store_name: str,
                     version: Optional[int] = None) -> int:
        """Pull this stage's parameter subtree from its weight-plane store
        (the per-stage weight placement path) and init fresh optimizer
        state for it."""
        from ray_tpu.weights import WeightStore

        self._build_programs()
        tree, version = WeightStore(store_name).pull(version,
                                                     return_version=True)
        self.params = tree["params"]
        self.opt_state = self._init_opt_state()
        self.step = 0
        return version

    # -- schedule execution ---------------------------------------------

    def _send(self, key: str, mb: int, payload, step: int, nbytes: int):
        from ray_tpu.util import tracing

        ctx = tracing.current_context()
        span_id = tracing.new_span_id()
        trace_id = ctx[0] if ctx else tracing.new_trace_id()
        t0 = time.time()
        self._chans[key].write({"mb": mb, "data": payload,
                                "trace": (trace_id, span_id)})
        tracing.record_span(
            "pipe.send", t0, time.time(), category="pipe",
            trace_id=trace_id, span_id=span_id,
            parent_id=ctx[1] if ctx else None,
            stage=self.stage, mb=mb, step=step, nbytes=nbytes)

    def _recv(self, key: str, mb: int, step: int):
        from ray_tpu.util import tracing

        t0 = time.time()
        msg = self._chans[key].read()
        if msg["mb"] != mb:
            raise RuntimeError(
                f"stage {self.stage} expected microbatch {mb} on {key}, "
                f"got {msg['mb']} — schedule/channel desync")
        tr = msg.get("trace")
        tracing.record_span(
            "pipe.recv", t0, time.time(), category="pipe",
            trace_id=tr[0] if tr else tracing.new_trace_id(),
            span_id=tracing.new_span_id(),
            parent_id=tr[1] if tr else None,
            stage=self.stage, mb=mb, step=step)
        return msg["data"]

    def run_schedule(self, step: int, ops: List, microbatches: Optional[List[dict]] = None) -> Dict[str, Any]:
        """Execute one step's op stream (``schedule.build_schedule`` row
        for this stage). ``microbatches`` carries the per-microbatch
        host data this stage consumes: ``tokens`` on the first stage,
        ``targets``/``mask`` on the last."""
        from ray_tpu.util import tracing

        self._build_programs()
        if self.params is None:
            raise RuntimeError(
                f"stage {self.stage}: init_weights/restore before running")
        jax = _jax()
        p = self.programs
        wall0 = time.perf_counter()
        compute_s = send_s = recv_s = 0.0
        send_bytes = recv_bytes = 0
        losses: List[float] = []
        auxes: List[float] = []
        self._acc = None
        self._inputs.clear()
        for kind, mb in ops:
            if kind == sched.RECV_F:
                t0 = time.perf_counter()
                x = self._recv("fwd_in", mb, step)
                recv_s += time.perf_counter() - t0
                recv_bytes += x.nbytes
                self._inputs[mb] = x
            elif kind == sched.FWD:
                if self.stage == 0:
                    self._inputs[mb] = microbatches[mb]["tokens"]
                x = self._inputs[mb]
                t0 = time.perf_counter()
                with tracing.profile("pipe.fwd", category="pipe",
                                     stage=self.stage, mb=mb, step=step):
                    if p.last:
                        # stash only: the BWD value_and_grad computes the
                        # loss — F and B are adjacent here, a separate
                        # forward would double this stage's compute
                        self._y = None
                    else:
                        y, aux = p.fwd(self.params, x)
                        jax.block_until_ready(y)
                        auxes.append(float(aux))
                        self._y = np.asarray(y)
                compute_s += time.perf_counter() - t0
            elif kind == sched.SEND_F:
                t0 = time.perf_counter()
                self._send("fwd_out", mb, self._y, step, self._y.nbytes)
                send_bytes += self._y.nbytes
                self._y = None
                send_s += time.perf_counter() - t0
            elif kind == sched.RECV_B:
                t0 = time.perf_counter()
                dy = self._recv("bwd_in", mb, step)
                recv_s += time.perf_counter() - t0
                recv_bytes += dy.nbytes
                self._dy = dy
            elif kind == sched.BWD:
                x = self._inputs.pop(mb)
                t0 = time.perf_counter()
                with tracing.profile("pipe.bwd", category="pipe",
                                     stage=self.stage, mb=mb, step=step):
                    if p.last:
                        loss, aux, dparams, dx = p.bwd(
                            self.params, x, microbatches[mb]["targets"],
                            microbatches[mb]["mask"])
                        losses.append(float(loss))
                        auxes.append(float(aux))
                    elif p.first:
                        dparams, dx = p.bwd(self.params, x, self._dy), None
                        self._dy = None
                    else:
                        dparams, dx = p.bwd(self.params, x, self._dy)
                        self._dy = None
                    self._acc = (dparams if self._acc is None
                                 else p.acc_grads(self._acc, dparams))
                    jax.block_until_ready(self._acc)
                    self._dx = None if dx is None else np.asarray(dx)
                compute_s += time.perf_counter() - t0
            elif kind == sched.SEND_B:
                t0 = time.perf_counter()
                self._send("bwd_out", mb, self._dx, step, self._dx.nbytes)
                send_bytes += self._dx.nbytes
                self._dx = None
                send_s += time.perf_counter() - t0
            else:
                raise ValueError(f"unknown schedule op {kind!r}")
        self._last_losses = losses
        reduce_launched = False
        if self.dp_group is not None and self._acc is not None:
            # kick every bucket's cross-replica allreduce NOW, async: the
            # collectives run while the controller is still collecting
            # results and coordinating the clip across stages
            self._launch_reduce()
            reduce_launched = True
        # goodput attribution from the per-op timers this schedule already
        # keeps: recv waits are the pipeline bubble (idle until a neighbor
        # produces), send waits block on channel backpressure
        from ray_tpu.util import goodput

        goodput.set_job(self.run_name)
        goodput.add("step_compute", compute_s)
        goodput.add("bubble", recv_s)
        goodput.add("collective_wait", send_s)
        goodput.count("steps")
        return {
            "stage": self.stage,
            "losses": losses,
            "aux": auxes,
            "wall_s": time.perf_counter() - wall0,
            "compute_s": compute_s,
            "send_s": send_s,
            "recv_wait_s": recv_s,
            "send_bytes": send_bytes,
            "recv_bytes": recv_bytes,
            "reduce_launched": reduce_launched,
        }

    def _launch_reduce(self):
        """Submit every bucket's grad allreduce to the async reducer (one
        ``train.bucket_allreduce`` span per bucket lands as each
        completes)."""
        import jax

        self._bucketing()
        flat, _ = jax.tree_util.tree_flatten_with_path(self._acc)
        by_path = {jax.tree_util.keystr(k): np.asarray(v) for k, v in flat}
        self._pending_reduce = [
            self._reducer.submit(b, {p: by_path[p] for p in b.paths})
            for b in self._bucket_plan.buckets
        ]

    def _collect_reduced(self):
        """Fold completed bucket allreduces back into the accumulated
        grad tree (idempotent; no-op without a dp group)."""
        if self._pending_reduce is None:
            return
        import jax

        from ray_tpu.util import goodput

        flat, treedef = jax.tree_util.tree_flatten_with_path(self._acc)
        paths = [jax.tree_util.keystr(k) for k, _ in flat]
        reduced: Dict[str, np.ndarray] = {}
        with goodput.region("collective_wait"):
            for handle in self._pending_reduce:
                reduced.update(handle.result())
        self._pending_reduce = None
        self._acc = jax.tree_util.tree_unflatten(
            treedef, [reduced[p] for p in paths])

    # -- step boundary ---------------------------------------------------

    def grad_sqnorm(self) -> float:
        if self._acc is None:
            raise RuntimeError(f"stage {self.stage}: no accumulated grads")
        self._collect_reduced()  # clip must see the cross-replica sum
        return float(self.programs.grad_sqnorm(self._acc))

    def apply_grads(self, scale: float) -> int:
        """Scale the accumulated grads (1/M and the coordinated global
        clip, folded into one factor by the controller) and step the
        optimizer. With ``bucket_bytes`` set the update applies bucket by
        bucket (per-bucket optimizer state, ``pipe.bucket_apply`` spans) —
        bit-identical to the whole-tree apply for per-leaf transforms."""
        if self._acc is None:
            raise RuntimeError(f"stage {self.stage}: no accumulated grads")
        self._collect_reduced()
        if not self.bucket_bytes:
            self.params, self.opt_state = self.programs.opt_apply(
                self._acc, scale, self.opt_state, self.params)
            self._acc = None
            self.step += 1
            return self.step
        import jax

        from ray_tpu.util import tracing

        self._bucketing()
        flat, treedef = jax.tree_util.tree_flatten_with_path(self.params)
        paths = [jax.tree_util.keystr(k) for k, _ in flat]
        by_path = dict(zip(paths, (v for _, v in flat)))
        gflat, _ = jax.tree_util.tree_flatten_with_path(self._acc)
        g_by_path = {jax.tree_util.keystr(k): v for k, v in gflat}
        for b in self._bucket_plan.buckets:
            with tracing.profile("pipe.bucket_apply", category="pipe",
                                 stage=self.stage, bucket=b.index,
                                 nbytes=b.nbytes, step=self.step):
                p_sub = {p: by_path[p] for p in b.paths}
                g_sub = {p: g_by_path[p] for p in b.paths}
                new_sub, self.opt_state[str(b.index)] = \
                    self.programs.opt_apply(g_sub, scale,
                                            self.opt_state[str(b.index)],
                                            p_sub)
                by_path.update(new_sub)
        self.params = jax.tree_util.tree_unflatten(
            treedef, [by_path[p] for p in paths])
        self._acc = None
        self.step += 1
        return self.step

    # -- ckpt plane -------------------------------------------------------

    def save_ckpt(self, ckpt_root: str, step: int) -> str:
        """Per-stage checkpoint through the ckpt plane: one manifest over
        content-addressed chunks per stage, spec-tagged with this stage's
        geometry so a restore onto a different gang shape reshards
        no-gather (ckpt.restore_shards)."""
        from ray_tpu import ckpt
        from ray_tpu.weights.spec import MeshSpec, ShardedTreeSpec

        tree = {"params": self.params, "opt_state": self.opt_state,
                "step": np.int64(step)}
        spec = ShardedTreeSpec.from_tree(
            tree, MeshSpec.host_mesh([f"stage{self.stage}"]))
        store = ckpt.CheckpointStore(
            os.path.join(ckpt_root, f"stage{self.stage}"),
            name=f"{self.run_name}-s{self.stage}")
        man = ckpt.save_checkpoint(store, tree, step=step, spec=spec)
        return man.ckpt_id

    def restore_ckpt(self, ckpt_root: str,
                     target_step: Optional[int] = None) -> Optional[int]:
        """Restore (params, opt_state, step) from this stage's latest
        manifest — or, with ``target_step``, the newest manifest at or
        below it (the controller's rollback when a crash mid-save left
        stages disagreeing). None when no usable checkpoint exists (the
        caller falls back to weight-plane init)."""
        from ray_tpu import ckpt

        self._build_programs()
        store = ckpt.CheckpointStore(
            os.path.join(ckpt_root, f"stage{self.stage}"),
            name=f"{self.run_name}-s{self.stage}")
        manifest = store.latest()
        if target_step is not None:
            cands = [m for m in store.list() if m.step <= target_step]
            manifest = max(cands, key=lambda m: m.step) if cands else None
        if manifest is None:
            return None
        tree = ckpt.restore_tree(store, manifest.ckpt_id)
        self.params = tree["params"]
        restored = tree["opt_state"]
        # bucketed opt state serializes as {bucket_index_str: state}; a
        # mode/bucket_bytes change between save and restore cannot be
        # silently adopted (apply_grads would index the wrong shape)
        was_bucketed = isinstance(restored, dict) and all(
            isinstance(k, str) and k.isdigit() for k in restored)
        if bool(self.bucket_bytes) != was_bucketed:
            raise RuntimeError(
                f"stage {self.stage}: checkpoint opt state is "
                f"{'bucketed' if was_bucketed else 'whole-tree'} but this "
                f"stage is configured {'bucketed' if self.bucket_bytes else 'whole-tree'} "
                f"— restore with the run's original bucket_bytes setting")
        if was_bucketed:
            plan = self._bucketing()
            expect = {str(b.index) for b in plan.buckets}
            if set(restored) != expect:
                raise RuntimeError(
                    f"stage {self.stage}: checkpoint has buckets "
                    f"{sorted(restored)} but the current plan has "
                    f"{sorted(expect)} — bucket_bytes changed between "
                    f"save and restore")
        self.opt_state = restored
        self.step = int(tree["step"])
        return self.step

    # -- introspection / teardown ----------------------------------------

    def state_digest(self) -> Dict[str, float]:
        """Cheap content fingerprint for tests (param/opt sums + step)."""
        jax = _jax()

        psum = float(sum(np.asarray(a, dtype=np.float64).sum()
                         for a in jax.tree.leaves(self.params)))
        return {"step": self.step, "param_sum": psum}

    def pull_params(self) -> Dict[str, Any]:
        """This stage's param subtree as host arrays (tests, small
        models; production consumers go through the weight plane)."""
        jax = _jax()

        return jax.tree.map(lambda a: np.asarray(a), self.params)

    def close_channels(self, unlink: bool = False) -> bool:
        for chan in self._chans.values():
            try:
                chan.close(unlink=unlink)
            except Exception:
                pass
        self._chans.clear()
        return True

    def shutdown(self) -> bool:
        if self._reducer is not None:
            try:
                self._reducer.shutdown()
            except Exception:
                pass
            self._reducer = None
        self.close_channels(unlink=True)
        return True


def _jax():
    from ray_tpu.utils import import_jax

    return import_jax()

"""PipelineTrainer: MPMD pipeline-parallel training across actor gangs.

One actor (gang) per stage, microbatches streamed between stages over the
channel plane under a 1F1B schedule (schedule.py), per-stage weight
placement through the weight plane, per-stage checkpoints through the ckpt
plane, and gang re-formation + manifest restore on stage death. The driver
stays a pure conductor: it ships the op streams and the step's host-side
microbatch data, coordinates the cross-stage global-norm clip, and never
touches an activation byte.

Loss/grad parity contract with the single-mesh ``TrainStepBundle``: equal
-size all-token microbatches make the mean of per-microbatch LM losses
equal the full-batch loss, grads accumulate as sums and apply with a
``clip_scale / M`` factor, and the coordinated clip (sqrt of the summed
per-stage sqnorms) reproduces ``optax.clip_by_global_norm`` exactly —
tests/test_pipeline_plane.py pins the 2-stage-vs-single-mesh equality.
"""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.train.pipeline import schedule as sched
from ray_tpu.train.pipeline.partition import (
    partition_layers,
    rank_chunk_keys,
    stage_param_keys,
)
from ray_tpu.train.pipeline.stage import PipelineStage, channel_shm_paths


@dataclass
class PipelineConfig:
    """Shape of the pipeline run (everything but the model itself)."""

    num_stages: int = 2
    num_microbatches: int = 4
    microbatch_size: int = 2
    seq_len: int = 128
    clip_global_norm: Optional[float] = 1.0
    ckpt_every: int = 0  # steps between per-stage checkpoints (0 = off)
    channel_capacity: int = 4 << 20
    # interleaved virtual stages (Megatron-style): each rank hosts this
    # many non-contiguous model chunks; bubble shrinks to
    # (S-1)/(S-1+V*M). V>1 requires num_microbatches % num_stages == 0.
    virtual_stages: int = 1
    # slots per channel edge: depth>=2 lets SEND_F overlap the next
    # compute op instead of blocking on the downstream ack
    channel_depth: int = 2
    # quantized activation streaming over the forward channels (None /
    # "int8" / "fp8" / "bf16" / "int8:128"-style spec). Gradients and
    # non-float leaves always stream exact; None is bitwise-identical to
    # the uncompressed path.
    activation_compression: Optional[str] = None
    step_timeout_s: float = 120.0
    max_recoveries: int = 3
    boundaries: Optional[List] = None  # explicit [start, stop) per stage
    # size-bounded bucketed optimizer apply on every stage (None = the
    # whole-tree apply). Per-bucket opt state + `pipe.bucket_apply` spans;
    # bit-identical to whole-tree apply for per-leaf transforms, and the
    # hook the stage-level dp_group replica allreduce rides on.
    bucket_bytes: Optional[int] = None

    @property
    def batch_size(self) -> int:
        return self.num_microbatches * self.microbatch_size


def make_microbatches(cfg: TransformerConfig, pipe: PipelineConfig,
                      seed: int, step: int) -> List[Dict[str, np.ndarray]]:
    """Deterministic synthetic LM microbatches for ``step`` (the parity
    tests regenerate the identical batch for the single-mesh side)."""
    rng = np.random.default_rng(seed * 1_000_003 + step)
    out = []
    for _ in range(pipe.num_microbatches):
        tok = rng.integers(0, cfg.vocab_size,
                           (pipe.microbatch_size, pipe.seq_len + 1),
                           dtype=np.int32)
        out.append({
            "tokens": tok[:, :-1],
            "targets": tok[:, 1:],
            "mask": np.ones((pipe.microbatch_size, pipe.seq_len),
                            np.float32),
        })
    return out


class PipelineTrainer:
    """Drive S stage actors through 1F1B steps with recovery.

    ``optimizer_factory`` (a zero-arg callable returning an optax
    transform, shipped to every stage) must exclude global-norm clipping —
    pass ``pipe.clip_global_norm`` instead and the controller coordinates
    it across stages.
    """

    def __init__(self, cfg: TransformerConfig, pipe: PipelineConfig,
                 *, params: Optional[Dict[str, Any]] = None,
                 optimizer_factory: Optional[Callable] = None,
                 ckpt_root: Optional[str] = None,
                 run_name: Optional[str] = None, seed: int = 0):
        import cloudpickle

        self.cfg = cfg
        self.pipe = pipe
        self.seed = seed
        self.run_name = run_name or f"pipe_{uuid.uuid4().hex[:8]}"
        self.ckpt_root = ckpt_root
        self.generation = 0
        self.step = 0
        self.last_saved_step: Optional[int] = None
        self.recoveries = 0
        self.restored_steps: List[int] = []
        self.history: List[Dict[str, Any]] = []  # per-step stats
        self._cfg_blob = cloudpickle.dumps(cfg)
        self._opt_blob = (cloudpickle.dumps(optimizer_factory)
                          if optimizer_factory else None)
        # with interleaving the partition cut is at virtual-stage (chunk)
        # granularity: P = S*V boundary ranges, chunk q living on rank q%S
        self.num_virtual = pipe.num_stages * pipe.virtual_stages
        self._bounds = (pipe.boundaries
                        or partition_layers(cfg.n_layers, self.num_virtual))
        self._schedule = sched.build_interleaved_schedule(
            pipe.num_stages, pipe.num_microbatches, pipe.virtual_stages)
        self.actors: List[Any] = []
        self._seed_weight_plane(params, seed)
        self._form_gang(restore=False)

    # -- weight plane: per-stage placement -------------------------------

    def _stage_store_name(self, stage: int) -> str:
        return f"{self.run_name}_s{stage}"

    def _seed_weight_plane(self, params: Optional[Dict[str, Any]],
                           seed: int):
        """Initialize the full model once on the driver, cut it at the
        stage boundaries, and publish each subtree durable into that
        stage's weight store — stages pull only their own slice (for
        models too big to init in one process, pass per-stage ``params``
        published out-of-band instead)."""
        from ray_tpu.utils import import_jax
        from ray_tpu.weights import WeightStore

        if params is None:
            jax = import_jax()
            import flax.linen as nn

            from ray_tpu.models.transformer import Transformer

            tokens = np.zeros((1, min(self.cfg.max_seq_len, 128)), np.int32)
            params = Transformer(self.cfg).init(
                jax.random.PRNGKey(seed), tokens)["params"]
            # strip flax's LogicallyPartitioned boxes: the weight plane's
            # flatten_tree sees plain containers only, and stage programs
            # consume raw arrays (their sharding comes from the stage mesh,
            # not the driver's logical annotations)
            params = nn.unbox(params)
        self.init_params = params
        self._stores = []
        # cut at chunk granularity, publish per RANK (a rank's store holds
        # the merge of its chunks' disjoint key sets; the stage re-splits)
        S = self.pipe.num_stages
        for s in range(S):
            sub = {k: params[k]
                   for keys in rank_chunk_keys(
                       self.cfg, s, S, self.pipe.virtual_stages,
                       self._bounds).values()
                   for k in keys}
            store = WeightStore(self._stage_store_name(s))
            store.publish({"params": sub}, durable=True)
            self._stores.append(store)

    # -- gang lifecycle ---------------------------------------------------

    def _form_gang(self, restore: bool):
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        pipe = self.pipe
        # stage hand-offs ride the shm channel slots, which only exist on
        # one node: pin the gang to the driver's node (cross-node stages —
        # the mailbox/ICI channel tiers — are the ROADMAP's round-2 item)
        here = ray_tpu.get_runtime_context().get_node_id()
        self.actors = [
            PipelineStage.options(
                num_cpus=1, max_restarts=0,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=here, soft=False)).remote(
                s, pipe.num_stages, self._cfg_blob, self._opt_blob,
                self.run_name, self.generation,
                channel_capacity=pipe.channel_capacity,
                boundaries=[list(b) for b in self._bounds],
                bucket_bytes=pipe.bucket_bytes,
                num_chunks=pipe.virtual_stages,
                channel_depth=pipe.channel_depth,
                activation_compression=pipe.activation_compression)
            for s in range(pipe.num_stages)
        ]
        ray_tpu.get([a.ready.remote() for a in self.actors], timeout=120)
        ray_tpu.get([a.create_channels.remote() for a in self.actors],
                    timeout=60)
        ray_tpu.get([a.open_channels.remote() for a in self.actors],
                    timeout=60)
        restored: Optional[int] = None
        if restore and self.ckpt_root:
            steps = ray_tpu.get(
                [a.restore_ckpt.remote(self.ckpt_root)
                 for a in self.actors], timeout=300)
            if all(s is not None for s in steps):
                restored = min(steps)
                if len(set(steps)) != 1:
                    # a crash raced the per-stage saves: roll every stage
                    # back to the newest step ALL of them committed
                    steps = ray_tpu.get(
                        [a.restore_ckpt.remote(self.ckpt_root, restored)
                         for a in self.actors], timeout=300)
                    if set(steps) != {restored}:
                        raise RuntimeError(
                            f"per-stage checkpoints cannot agree on a "
                            f"common step (got {steps}); the run needs a "
                            f"manual prune under {self.ckpt_root}")
        if restored is None:
            ray_tpu.get(
                [a.init_weights.remote(self._stage_store_name(s))
                 for s, a in enumerate(self.actors)], timeout=300)
            restored = 0
        self.step = restored

    def _kill_gang(self):
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self.actors = []
        # a dead writer cannot unlink its shm slots; reclaim them here so
        # generations never accumulate segments
        for path in channel_shm_paths(self.run_name, self.generation,
                                      self.pipe.num_stages,
                                      self.pipe.virtual_stages):
            try:
                os.unlink(path)
            except OSError:
                pass

    def _recover(self, err: Exception):
        from ray_tpu.util import goodput

        self.recoveries += 1
        if self.recoveries > self.pipe.max_recoveries:
            raise RuntimeError(
                f"pipeline gang failed {self.recoveries}x "
                f"(max {self.pipe.max_recoveries}); last: {err}") from err
        t0 = time.monotonic()
        self._kill_gang()
        self.generation += 1
        self._form_gang(restore=True)
        self.restored_steps.append(self.step)
        goodput.set_job(self.run_name)
        goodput.add("reform_downtime", time.monotonic() - t0)
        goodput.count("reforms")

    # -- training ---------------------------------------------------------

    def _wait_all(self, refs: List, timeout: float) -> List[Any]:
        """wait-any loop (the TrainController idiom): a failure on ANY
        stage surfaces immediately instead of blocking behind stage 0."""
        by_idx: Dict[int, Any] = {}
        pending = {ref: i for i, ref in enumerate(refs)}
        deadline = time.monotonic() + timeout
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"{len(pending)} pipeline stages stuck past "
                    f"{timeout}s — a dead neighbor wedges the schedule")
            ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                    timeout=remaining)
            for ref in ready:
                by_idx[pending.pop(ref)] = ray_tpu.get(ref, timeout=60)
        return [by_idx[i] for i in range(len(refs))]

    def _run_step(self, microbatches: List[Dict[str, np.ndarray]]
                  ) -> Dict[str, Any]:
        pipe = self.pipe
        S = pipe.num_stages
        refs = []
        for s, actor in enumerate(self.actors):
            data = None
            if s == 0 or s == S - 1:
                data = microbatches
            refs.append(actor.run_schedule.remote(
                self.step, [tuple(op) for op in self._schedule[s]], data))
        results = self._wait_all(refs, pipe.step_timeout_s)
        # coordinated global-norm clip: one scale for every stage
        scale = 1.0 / pipe.num_microbatches
        gnorm = None
        if pipe.clip_global_norm:
            sq = self._wait_all(
                [a.grad_sqnorm.remote() for a in self.actors], 60.0)
            gnorm = float(np.sqrt(sum(sq))) / pipe.num_microbatches
            clip = pipe.clip_global_norm
            scale *= clip / max(gnorm, clip)
        self._wait_all([a.apply_grads.remote(scale) for a in self.actors],
                       60.0)
        last = results[-1]
        coef = self.cfg.moe_aux_coef
        # the final virtual stage's loss already includes ITS aux term;
        # fold in every other chunk's aux so the reported loss matches the
        # single-mesh objective. Keyed by microbatch (aux_by_mb) — with
        # interleaving a rank's aux arrives in virtual-microbatch order,
        # not microbatch order.
        upstream_aux = float(np.mean([
            sum(r["aux_by_mb"].get(i, 0.0) for r in results)
            for i in range(pipe.num_microbatches)])) \
            if self.num_virtual > 1 else 0.0
        loss = float(np.mean(last["losses"])) + coef * upstream_aux
        stats = {
            "step": self.step,
            "loss": loss,
            "losses_mb": last["losses"],
            "grad_norm": gnorm,
            "wall_s": max(r["wall_s"] for r in results),
            "compute_s": [r["compute_s"] for r in results],
            "recv_wait_s": [r["recv_wait_s"] for r in results],
            "send_bytes": [r["send_bytes"] for r in results],
            "hop": [r["hop"] for r in results],
            "activation_bytes_per_mb": (
                results[0]["send_bytes"] // pipe.num_microbatches
                if S > 1 else 0),
        }
        return stats

    def train(self, num_steps: int) -> List[Dict[str, Any]]:
        """Run until ``self.step == num_steps`` (absolute), recovering
        from stage death by re-forming the gang and restoring the last
        per-stage checkpoints. Returns the per-step stats appended this
        call."""
        out = []
        while self.step < num_steps:
            microbatches = make_microbatches(self.cfg, self.pipe, self.seed,
                                             self.step)
            try:
                stats = self._run_step(microbatches)
            except Exception as e:  # stage death / wedged schedule
                self._recover(e)
                continue  # re-run from the restored step
            self.step += 1
            self.history.append(stats)
            out.append(stats)
            if (self.pipe.ckpt_every and self.ckpt_root
                    and self.step % self.pipe.ckpt_every == 0):
                try:
                    self.save()
                except Exception as e:  # a stage died mid-save: the
                    # re-formed gang rolls back to the newest step ALL
                    # stages committed (partial manifests are ignored)
                    self._recover(e)
        return out

    def save(self) -> List[str]:
        ids = self._wait_all(
            [a.save_ckpt.remote(self.ckpt_root, self.step)
             for a in self.actors], 300.0)
        self.last_saved_step = self.step
        return ids

    def merged_params(self) -> Dict[str, Any]:
        """Pull and merge every stage's params (tests/small models)."""
        from ray_tpu.train.pipeline.partition import merge_params

        return merge_params(self._wait_all(
            [a.pull_params.remote() for a in self.actors], 120.0))

    def shutdown(self):
        for a in self.actors:
            try:
                ray_tpu.get(a.shutdown.remote(), timeout=10)
            except Exception:
                pass
        self._kill_gang()
        for store in getattr(self, "_stores", []):
            try:
                store.shutdown()
            except Exception:
                pass


def repartition_manifest_leaves(ckpt_root: str, cfg: TransformerConfig,
                                old_stages: int, new_stages: int
                                ) -> List[List[str]]:
    """Stage-granularity resharding map: for each NEW stage, which leaf
    paths to read from which OLD stage manifests. Pure planning (the
    actual reads go through ckpt.restore_shards per stage, chunk-sliced —
    no stage ever reads a byte outside its slice; the plan is no-gather
    by construction because param keys partition disjointly)."""
    old_keys = [set(stage_param_keys(cfg, s, old_stages))
                for s in range(old_stages)]
    out = []
    for s in range(new_stages):
        need = stage_param_keys(cfg, s, new_stages)
        rows = []
        for key in need:
            src = next(i for i, ks in enumerate(old_keys) if key in ks)
            rows.append(f"stage{src}:{key}")
        out.append(rows)
    return out

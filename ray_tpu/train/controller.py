"""TrainController: the actor orchestrating one training run.

Reference: train/v2/_internal/execution/controller/controller.py:100 — a
state machine that creates the worker group, polls it, applies the failure
policy (kill group -> recreate -> resume from latest checkpoint), and owns
the checkpoint manager.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import TaskError
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig


@ray_tpu.remote
class TrainController:
    """max_concurrency > 1 so _on_report lands while run() blocks."""

    def __init__(self, fn_blob: bytes, config: Optional[dict],
                 scaling: ScalingConfig, run_config: RunConfig,
                 run_dir: str, shards_per_rank: Optional[List[bytes]] = None):
        self.fn_blob = fn_blob
        self.config = config
        self.scaling = scaling
        self.run_config = run_config
        self.run_dir = run_dir
        self.shards_per_rank = shards_per_rank
        ckpt_cfg = run_config.checkpoint_config
        self.manager = CheckpointManager(
            run_dir, ckpt_cfg.num_to_keep, ckpt_cfg.checkpoint_score_attribute,
            ckpt_cfg.checkpoint_score_order)
        self._lock = threading.Lock()
        self.latest_metrics: Dict[str, Any] = {}
        self.state = "INITIALIZING"
        self._self_handle = None

    def _set_self(self, handle):
        self._self_handle = handle
        return True

    def _on_report(self, rank: int, metrics: Dict[str, Any],
                   ckpt_ref) -> bool:
        """``ckpt_ref`` is a checkpoint-plane manifest id (the worker-side
        async save path — chunks may still be committing when this lands),
        a ``{"dir": path}`` fallback for bare contexts, or None."""
        with self._lock:
            if rank == 0:
                self.latest_metrics = dict(metrics)
            if isinstance(ckpt_ref, str):
                self.manager.register_manifest(ckpt_ref, metrics)
            elif isinstance(ckpt_ref, dict) and ckpt_ref.get("dir"):
                self.manager.register(ckpt_ref["dir"], metrics)
        return True

    def status(self) -> Dict[str, Any]:
        return {"state": self.state, "metrics": dict(self.latest_metrics)}

    def _shards_for(self, size: int) -> Optional[List[bytes]]:
        """Dataset shards for a (possibly resized) group. An elastic shrink
        keeps the first ``size`` rank shards; the removed ranks' shards are
        dropped with a warning (full re-sharding needs the dataset layer)."""
        if self.shards_per_rank is None:
            return None
        if size < len(self.shards_per_rank):
            import logging

            logging.getLogger("ray_tpu.train").warning(
                "elastic shrink to %d workers drops the dataset shards of "
                "ranks >= %d for this restart", size, size)
        return self.shards_per_rank[:size]

    def run(self) -> Dict[str, Any]:
        from ray_tpu.train.scaling_policy import make_scaling_policy, sized
        from ray_tpu.train.worker_group import WorkerGroup
        from ray_tpu.util import goodput

        goodput.set_job(self.run_dir.rsplit("/", 1)[-1])
        reform_started: Optional[float] = None
        failures = 0
        max_failures = self.run_config.failure_config.max_failures
        last_error = None
        policy = make_scaling_policy(self.scaling)
        size = policy.initial_size(ray_tpu.available_resources())
        if size < 1:
            size = self.scaling.num_workers  # scheduler queues until ready
        while True:
            self.state = "SCHEDULING"
            scaling = sized(self.scaling, size)
            group = None
            try:
                # bounded group formation on elastic retries: if a stale
                # availability view sized too big, the PG never becomes
                # ready — fail into the retry loop (which re-sizes from a
                # fresher view) instead of hanging on it
                group = WorkerGroup(
                    scaling,
                    ready_timeout=60.0 if (failures and self.scaling.elastic)
                    else 600.0)
                bootstrap = scaling.bootstrap_distributed
                if bootstrap is None:
                    bootstrap = scaling.use_tpu and size > 1
                if bootstrap and size > 1:
                    group.bootstrap_distributed()
                if scaling.grad_sync_backend and size > 1:
                    # bucketed grad collectives for the loop (the group
                    # name carries the restart count: a re-formed group
                    # must not collide with the dead one's store actor)
                    group.setup_grad_sync(
                        f"train.grads.{self.run_dir.rsplit('/', 1)[-1]}"
                        f".r{failures}",
                        backend=scaling.grad_sync_backend,
                        bucket_bytes=scaling.grad_sync_bucket_bytes,
                        compression=getattr(scaling,
                                            "grad_sync_compression", None))
                self.state = "RUNNING"
                if reform_started is not None:
                    # downtime window: first failure detection through the
                    # re-formed group going back to RUNNING
                    goodput.add("reform_downtime",
                                time.monotonic() - reform_started)
                    goodput.count("reforms")
                    reform_started = None
                refs = group.run(self.fn_blob, self.config, self._self_handle,
                                 self.manager.latest(), self.run_dir,
                                 self._shards_for(size))
                # wait-any, not rank-ordered get: a failure on ANY worker
                # must trigger recovery immediately — a plain get(refs)
                # blocks on rank 0 and never notices rank k>0 dying
                # (reference: the controller's worker poll, controller.py:269)
                by_idx: Dict[int, Any] = {}
                pending = {ref: i for i, ref in enumerate(refs)}
                run_deadline = time.monotonic() + 24 * 3600
                while pending:
                    remaining = run_deadline - time.monotonic()
                    if remaining <= 0:
                        # a wedged worker (no result, no error) must still
                        # fall into the failure policy, like the old
                        # bounded get did
                        raise TimeoutError(
                            f"{len(pending)} train workers produced no "
                            f"result within 24h")
                    ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                            timeout=min(remaining, 3600.0))
                    for ref in ready:
                        by_idx[pending.pop(ref)] = ray_tpu.get(
                            ref, timeout=300)  # raises the worker's error
                results = [by_idx[i] for i in range(len(refs))]
                self.state = "FINISHED"
                latest = self.manager.latest()
                return {
                    "metrics": self.latest_metrics or (
                        results[0].get("result") if isinstance(results[0], dict)
                        else {}),
                    "checkpoint_path": latest.path if latest else None,
                    "num_workers": size,
                    "error": None,
                }
            except Exception as e:  # worker failure, PG timeout, node loss
                last_error = str(e)
                failures += 1
                self.state = "RESTARTING"
                if reform_started is None:
                    reform_started = time.monotonic()
                if failures > max_failures:
                    latest = self.manager.latest()
                    self.state = "ERRORED"
                    return {
                        "metrics": self.latest_metrics,
                        "checkpoint_path": latest.path if latest else None,
                        "num_workers": size,
                        "error": f"train workers failed {failures}x "
                                 f"(max_failures={max_failures}): {last_error[:2000]}",
                    }
                if group is not None:  # creation itself may have raised
                    group.shutdown()  # release resources BEFORE re-sizing
                group = None
                if self.scaling.elastic:
                    # settle: size from a view taken AFTER the GCS node
                    # death-detection window (health_check timeout + slack)
                    # and stable across samples, or an elastic resize could
                    # target capacity that is about to be marked dead
                    from ray_tpu._private.config import RAY_CONFIG

                    time.sleep(
                        (RAY_CONFIG.health_check_timeout_ms
                         + 3 * RAY_CONFIG.health_check_period_ms) / 1000.0)
                    avail = ray_tpu.available_resources()
                    for _ in range(10):
                        time.sleep(1.5)
                        nxt = ray_tpu.available_resources()
                        if nxt == avail:
                            break
                        avail = nxt
                else:
                    time.sleep(1.0)
                    avail = {}  # fixed policy ignores the view
                new_size = policy.size_after_failure(size, avail)
                if new_size is None:
                    latest = self.manager.latest()
                    self.state = "ERRORED"
                    return {
                        "metrics": self.latest_metrics,
                        "checkpoint_path": latest.path if latest else None,
                        "num_workers": size,
                        "error": ("cluster below the elastic minimum "
                                  f"({self.scaling.min_workers} workers): "
                                  f"{last_error[:1500]}"),
                    }
                size = new_size
            finally:
                if group is not None:
                    group.shutdown()

"""TrainController: the actor orchestrating one training run.

Reference: train/v2/_internal/execution/controller/controller.py:100 — a
state machine that creates the worker group, polls it, applies the failure
policy (kill group -> recreate -> resume from latest checkpoint), and owns
the checkpoint manager.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import TaskError
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig


@ray_tpu.remote
class TrainController:
    """max_concurrency > 1 so _on_report lands while run() blocks."""

    def __init__(self, fn_blob: bytes, config: Optional[dict],
                 scaling: ScalingConfig, run_config: RunConfig,
                 run_dir: str, shards_per_rank: Optional[List[bytes]] = None):
        self.fn_blob = fn_blob
        self.config = config
        self.scaling = scaling
        self.run_config = run_config
        self.run_dir = run_dir
        self.shards_per_rank = shards_per_rank
        ckpt_cfg = run_config.checkpoint_config
        self.manager = CheckpointManager(
            run_dir, ckpt_cfg.num_to_keep, ckpt_cfg.checkpoint_score_attribute,
            ckpt_cfg.checkpoint_score_order)
        self._lock = threading.Lock()
        self.latest_metrics: Dict[str, Any] = {}
        self.state = "INITIALIZING"
        self._self_handle = None

    def _set_self(self, handle):
        self._self_handle = handle
        return True

    def _on_report(self, rank: int, metrics: Dict[str, Any],
                   staged_ckpt_dir: Optional[str]) -> bool:
        with self._lock:
            if rank == 0:
                self.latest_metrics = dict(metrics)
            if staged_ckpt_dir:
                self.manager.register(staged_ckpt_dir, metrics)
                import shutil

                shutil.rmtree(staged_ckpt_dir, ignore_errors=True)
        return True

    def status(self) -> Dict[str, Any]:
        return {"state": self.state, "metrics": dict(self.latest_metrics)}

    def run(self) -> Dict[str, Any]:
        from ray_tpu.train.worker_group import WorkerGroup

        failures = 0
        max_failures = self.run_config.failure_config.max_failures
        last_error = None
        while True:
            self.state = "SCHEDULING"
            group = WorkerGroup(self.scaling)
            try:
                bootstrap = self.scaling.bootstrap_distributed
                if bootstrap is None:
                    bootstrap = self.scaling.use_tpu and self.scaling.num_workers > 1
                if bootstrap and self.scaling.num_workers > 1:
                    group.bootstrap_distributed()
                self.state = "RUNNING"
                refs = group.run(self.fn_blob, self.config, self._self_handle,
                                 self.manager.latest(), self.run_dir,
                                 self.shards_per_rank)
                results = ray_tpu.get(refs, timeout=24 * 3600)
                self.state = "FINISHED"
                latest = self.manager.latest()
                return {
                    "metrics": self.latest_metrics or (
                        results[0].get("result") if isinstance(results[0], dict)
                        else {}),
                    "checkpoint_path": latest.path if latest else None,
                    "error": None,
                }
            except TaskError as e:
                last_error = str(e)
                failures += 1
                self.state = "RESTARTING"
                if failures > max_failures:
                    latest = self.manager.latest()
                    self.state = "ERRORED"
                    return {
                        "metrics": self.latest_metrics,
                        "checkpoint_path": latest.path if latest else None,
                        "error": f"train workers failed {failures}x "
                                 f"(max_failures={max_failures}): {last_error[:2000]}",
                    }
                time.sleep(1.0)
            finally:
                group.shutdown()

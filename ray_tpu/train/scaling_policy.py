"""Scaling policies: how the Train controller sizes the worker group.

Reference: train/v2/_internal/execution/scaling_policy/scaling_policy.py:32
(the interface designed for elasticity) and fixed.py:13 (the fixed policy).

TPU-first elasticity (SURVEY.md §7 hard part (b)): a jax.distributed mesh
cannot shrink in place — elastic recovery means killing the group and
re-forming FRESH processes at a smaller world size, and that size must be
mesh-shaped: a whole number of ICI slices (``granularity=N``) or a power
of two (``granularity="pow2"``), never an arbitrary count.
"""

from __future__ import annotations

import logging
from dataclasses import replace
from typing import Dict, Optional

from ray_tpu.train.config import ScalingConfig

logger = logging.getLogger("ray_tpu.train")


class ScalingPolicy:
    """Decides worker-group sizes over the run's lifetime."""

    def initial_size(self, available: Dict[str, float]) -> int:
        raise NotImplementedError

    def size_after_failure(self, current: int,
                           available: Dict[str, float]) -> Optional[int]:
        """New group size after a failure, or None to give up resizing
        (the failure policy then counts it as a plain restart failure)."""
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured size (reference: scaling_policy/fixed.py:13)."""

    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling

    def initial_size(self, available):
        return self.scaling.num_workers

    def size_after_failure(self, current, available):
        return self.scaling.num_workers  # same shape, fresh processes


class ElasticScalingPolicy(ScalingPolicy):
    """Re-form at the largest mesh-shaped size the cluster can host.

    On worker loss the group restarts at
    ``min(num_workers, max feasible by available resources)`` rounded DOWN
    to the granularity (whole slices / power of two), bounded below by
    ``min_workers`` — e.g. losing 1 of 4 single-CPU workers on a shrunken
    cluster re-forms at 2, not 3.
    """

    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling
        self.min_workers = max(1, scaling.min_workers)

    def _max_feasible(self, available: Dict[str, float]) -> int:
        per = self.scaling.bundle()
        counts = [int(available.get(k, 0.0) // v)
                  for k, v in per.items() if v > 0]
        return min(counts) if counts else 0

    def _round_to_shape(self, n: int) -> int:
        g = self.scaling.elastic_granularity
        if g == "pow2":
            size = 1
            while size * 2 <= n:
                size *= 2
            return size if n >= 1 else 0
        step = max(1, int(g))
        return (n // step) * step

    def initial_size(self, available):
        feasible = min(self.scaling.num_workers,
                       self._max_feasible(available))
        size = self._round_to_shape(feasible)
        return max(size, 0)

    def size_after_failure(self, current, available):
        size = self._round_to_shape(
            min(self.scaling.num_workers, self._max_feasible(available)))
        if size < self.min_workers:
            return None  # cluster too small even for the floor
        if size != current:
            logger.warning(
                "elastic resize: worker group re-forming at %d (was %d)",
                size, current)
        return size


def make_scaling_policy(scaling: ScalingConfig) -> ScalingPolicy:
    if scaling.elastic:
        return ElasticScalingPolicy(scaling)
    return FixedScalingPolicy(scaling)


def mesh_spec_for(num_workers: int, axis: str = "data"):
    """The weight-plane mesh a worker group of this size forms: a 1-D mesh
    with one device per worker, host ids ``rank<i>``.

    This is the re-form contract for elastic resharding: an incarnation of
    size N publishes its sharded state against ``mesh_spec_for(N)``; the
    re-formed incarnation of size M (a DIFFERENT mesh-shaped size chosen by
    the scaling policy) pulls against ``mesh_spec_for(M)`` and the planner
    moves only the shard slices that change hosts — no rank ever gathers
    the full state (see ray_tpu/weights/README.md).
    """
    from ray_tpu.weights.spec import MeshSpec

    return MeshSpec(shape=(num_workers,), axis_names=(axis,),
                    hosts=tuple(f"rank{i}" for i in range(num_workers)))


def sized(scaling: ScalingConfig, num_workers: int) -> ScalingConfig:
    return replace(scaling, num_workers=num_workers)

"""Per-worker train context: rank info + report plumbing.

Reference: ray.train.get_context() / ray.train.report (train/v2 api);
``report(metrics, checkpoint=...)`` ships the checkpoint to storage and
notifies the controller (checkpoint/report_handler in the reference).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_ctx = threading.local()


class TrainContext:
    def __init__(self, rank: int, world_size: int, local_rank: int,
                 node_rank: int, controller, latest_checkpoint: Optional[Checkpoint],
                 config: Optional[Dict[str, Any]] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.controller = controller
        self.latest_checkpoint = latest_checkpoint
        self.config = config or {}
        self.dataset_shards = dataset_shards or {}

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}")
        return shard


def set_context(ctx: Optional[TrainContext]):
    _ctx.value = ctx


def get_context() -> TrainContext:
    ctx = getattr(_ctx, "value", None)
    if ctx is None:
        raise RuntimeError("not inside a train worker (ray_tpu.train loop)")
    return ctx


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    """Report metrics (all ranks) and optionally a checkpoint (rank 0 ships
    it to storage via the controller; other ranks' checkpoints are ignored in
    round 1 — single-writer checkpoint layout)."""
    import shutil
    import uuid

    import ray_tpu

    ctx = get_context()
    ckpt_dir = None
    if checkpoint is not None and ctx.rank == 0:
        # stage into the (shared) run dir so the controller can adopt it even
        # if this worker's scratch space vanishes
        run_dir = getattr(ctx, "run_dir", None)
        src = checkpoint.as_directory()
        if run_dir:
            ckpt_dir = f"{run_dir}/staged_{uuid.uuid4().hex[:8]}"
            shutil.copytree(src, ckpt_dir, dirs_exist_ok=True)
        else:
            ckpt_dir = src
    ray_tpu.get(ctx.controller._on_report.remote(ctx.rank, metrics, ckpt_dir),
                timeout=300)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_context().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_context().get_dataset_shard(name)

"""Per-worker train context: rank info + report plumbing.

Reference: ray.train.get_context() / ray.train.report (train/v2 api);
``report(metrics, checkpoint=...)`` ships the checkpoint to storage and
notifies the controller (checkpoint/report_handler in the reference).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_ctx = threading.local()


class TrainContext:
    def __init__(self, rank: int, world_size: int, local_rank: int,
                 node_rank: int, controller, latest_checkpoint: Optional[Checkpoint],
                 config: Optional[Dict[str, Any]] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 grad_sync: Optional[Dict[str, Any]] = None):
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.controller = controller
        self.latest_checkpoint = latest_checkpoint
        self.config = config or {}
        self.dataset_shards = dataset_shards or {}
        # {"group": name, "world_size": N, "backend": ..., "bucket_bytes":
        # B} when the worker group set up bucketed grad sync (the
        # collective groups are already initialized in this process)
        self.grad_sync = grad_sync

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}")
        return shard

    # -- bucketed grad sync (collective/bucketed.py) ----------------------

    def _require_grad_sync(self) -> Dict[str, Any]:
        if not self.grad_sync:
            raise RuntimeError(
                "grad sync is not configured for this worker group — set "
                "ScalingConfig.grad_sync_backend")
        return self.grad_sync

    def make_bucket_reducer(self, params_like: Any,
                            compression: Any = "__default__"):
        """An AsyncBucketReducer over this group's grad-sync plane, with a
        bucket plan derived from ``params_like`` (every worker must build
        it over the same tree — bucket order is the collective order).
        Rides the dedicated ``.user`` sibling group so it can never
        interleave with a sharded optimizer's internal reducer; keep at
        most ONE live reducer per worker. ``compression`` defaults to
        ``ScalingConfig.grad_sync_compression`` (pass None/int8/fp8/bf16
        to override per reducer; every rank must pick the same)."""
        from ray_tpu.collective.bucketed import (AsyncBucketReducer,
                                                 leaf_meta, plan_buckets)

        gs = self._require_grad_sync()
        if compression == "__default__":
            compression = gs.get("compression")
        plan = plan_buckets(leaf_meta(params_like),
                            bucket_bytes=gs["bucket_bytes"],
                            world_size=self.world_size)
        return AsyncBucketReducer(f"{gs['group']}.user", plan,
                                  compression=compression)

    def make_sharded_optimizer(self, optimizer, params, *,
                               clip_global_norm: Optional[float] = None,
                               grad_scale: float = 1.0,
                               compression: Any = "__default__"):
        """A cross-replica ShardedBucketOptimizer: this worker keeps
        optimizer state only for its ~1/world_size of the buckets and the
        update pipeline overlaps bucket collectives with bucket applies.

        ``optimizer`` must be a PER-LEAF transform (adam family etc.) —
        it is applied bucket by bucket, so a cross-leaf transform like
        ``optax.clip_by_global_norm`` inside it would clip per-bucket
        norms; pass ``clip_global_norm=`` instead (computed globally from
        shard-local sqnorms)."""
        from ray_tpu.collective.bucketed import (ShardedBucketOptimizer,
                                                 leaf_meta, plan_buckets)

        gs = self._require_grad_sync()
        if compression == "__default__":
            compression = gs.get("compression")
        plan = plan_buckets(leaf_meta(params),
                            bucket_bytes=gs["bucket_bytes"],
                            world_size=self.world_size)
        return ShardedBucketOptimizer(
            gs["group"], plan, self.rank, optimizer, params,
            clip_global_norm=clip_global_norm, grad_scale=grad_scale,
            compression=compression)


def set_context(ctx: Optional[TrainContext]):
    _ctx.value = ctx


def get_context() -> TrainContext:
    ctx = getattr(_ctx, "value", None)
    if ctx is None:
        raise RuntimeError("not inside a train worker (ray_tpu.train loop)")
    return ctx


_savers: Dict[str, Any] = {}  # store root -> per-process async saver
_savers_lock = threading.Lock()


def _saver_for(run_dir: str):
    from ray_tpu.ckpt import CheckpointSaver
    from ray_tpu.train.checkpoint import checkpoint_store

    store = checkpoint_store(run_dir)
    with _savers_lock:
        saver = _savers.get(store.root)
        if saver is None:
            saver = _savers[store.root] = CheckpointSaver(store)
        return saver


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    """Report metrics (all ranks) and optionally a checkpoint (rank 0 ships
    it through the checkpoint plane; other ranks' checkpoints are ignored in
    round 1 — single-writer checkpoint layout).

    The save is ASYNC: the checkpoint directory's bytes are snapshotted to
    RAM here (so the caller may delete the directory immediately), the
    chunk writes + manifest commit happen on a background thread, and only
    the manifest id rides the report RPC. A second checkpointed report
    while the previous save is still writing blocks then (backpressure),
    never mid-step."""
    import ray_tpu
    from ray_tpu.train.checkpoint import dir_to_tree
    from ray_tpu.util import goodput

    ctx = get_context()
    if "mfu" in metrics:
        try:
            goodput.note_mfu(float(metrics["mfu"]))
        except (TypeError, ValueError):
            pass
    ckpt_ref = None
    if checkpoint is not None and ctx.rank == 0:
        run_dir = getattr(ctx, "run_dir", None)
        if run_dir:
            step = int(metrics.get("step", metrics.get(
                "training_iteration", 0)) or 0)
            tree = dir_to_tree(checkpoint.as_directory())
            ckpt_ref = _saver_for(run_dir).save(tree, step=step,
                                                metrics=metrics)
        else:
            # no shared run dir (a bare context in unit tests): hand the
            # directory itself over; the controller saves it blocking
            ckpt_ref = {"dir": checkpoint.as_directory()}
    ray_tpu.get(ctx.controller._on_report.remote(ctx.rank, metrics, ckpt_ref),
                timeout=300)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_context().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_context().get_dataset_shard(name)

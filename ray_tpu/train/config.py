"""Train configuration dataclasses.

Reference: python/ray/air/config.py (ScalingConfig/RunConfig/CheckpointConfig)
+ train/v2 failure policy config (v2/_internal/execution/failure_handling/).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    # resources per training worker actor
    resources_per_worker: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})
    chips_per_worker: int = 0  # TPU chips each worker owns (0 = all on its host)
    topology: Optional[str] = None  # e.g. "v5e-16" — selects a slice pod type
    placement_strategy: str = "SPREAD"
    # bootstrap jax.distributed across workers (multi-host SPMD). Defaults on
    # for multi-worker TPU groups.
    bootstrap_distributed: Optional[bool] = None
    # elasticity (reference: scaling_policy.py:32): on worker loss, re-form
    # the group at the largest mesh-shaped size the cluster can host
    # instead of insisting on num_workers
    elastic: bool = False
    min_workers: int = 1
    # mesh-shaped sizes only: "pow2" (powers of two) or an int slice size
    # (group size must be a whole multiple — TPU slice granularity)
    elastic_granularity: Any = "pow2"
    # bucketed grad synchronization across the group (the explicit-
    # collective tier of the overlapped train step): backend "cpu" (CI) or
    # "xla" (device collectives); None = off. Train loops reach it via
    # train.get_context().grad_sync / make_bucket_reducer /
    # make_sharded_optimizer (cross-replica sharded update: opt state
    # 1/N per worker).
    grad_sync_backend: Optional[str] = None
    grad_sync_bucket_bytes: int = 32 << 20
    # wire compression for the bucketed grad sync (collective/quant.py):
    # None (fp32, bit-identical to the uncompressed tier), "int8" / "fp8"
    # (block-quantized with error feedback; ~4x fewer wire bytes) or
    # "bf16" (plain narrowing, 2x). Strictly opt-in; CPU backend only at
    # this tier — on-device programs use TrainStepBundle(compression=...).
    grad_sync_compression: Optional[str] = None
    # collective dtype of the TrainStepBundle sharded-path grad
    # reduce-scatter: "fp32" (default, preserves the PR 12 bit-exact
    # contract) or "bf16" (halves collective bytes; optimizer + params
    # stay fp32 master copies). Composes with grad_sync_compression.
    grad_dtype: str = "fp32"

    def bundle(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if self.use_tpu and self.chips_per_worker:
            res["TPU"] = float(self.chips_per_worker)
        return res


@dataclass
class FailureConfig:
    max_failures: int = 0  # worker-group restarts allowed


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # local path or fsspec-style URI
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)


@dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional["Checkpoint"]
    error: Optional[str] = None
    path: str = ""


from ray_tpu.train.checkpoint import Checkpoint  # noqa: E402  (re-export cycle)

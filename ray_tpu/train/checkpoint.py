"""Checkpoints + top-K retention.

Reference: ray.train.Checkpoint (directory handle) and ``CheckpointManager``
(train/v2/_internal/execution/checkpoint/checkpoint_manager.py:71) persisting
through a storage context (execution/storage.py:312). Round 1 storage is a
filesystem path (local or NFS/gcsfuse mount); orbax handles the array state
inside the directory (see ray_tpu/train/orbax_utils.py).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional


class Checkpoint:
    """A directory of checkpoint artifacts."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: str) -> str:
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Tracks reported checkpoints under <storage>/<run>/checkpoint_NNNNNN,
    keeps top-K by the configured score attribute."""

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self.index = 0
        self.records: List[Dict[str, Any]] = []
        os.makedirs(run_dir, exist_ok=True)
        self._load_state()

    def _state_path(self) -> str:
        return os.path.join(self.run_dir, "checkpoint_manager.json")

    def _load_state(self):
        try:
            with open(self._state_path()) as f:
                state = json.load(f)
            self.index = state["index"]
            self.records = state["records"]
        except (FileNotFoundError, json.JSONDecodeError, KeyError):
            pass

    def _save_state(self):
        with open(self._state_path(), "w") as f:
            json.dump({"index": self.index, "records": self.records}, f)

    def register(self, source_dir: str, metrics: Dict[str, Any]) -> Checkpoint:
        """Persist a worker-reported checkpoint directory into the run dir."""
        self.index += 1
        dest = os.path.join(self.run_dir, f"checkpoint_{self.index:06d}")
        if os.path.abspath(source_dir) != dest:
            shutil.copytree(source_dir, dest, dirs_exist_ok=True)
        self.records.append({"path": dest, "metrics": metrics, "time": time.time()})
        self._prune()
        self._save_state()
        return Checkpoint(dest)

    def _prune(self):
        if self.num_to_keep is None or len(self.records) <= self.num_to_keep:
            return
        if self.score_attribute:
            sign = 1 if self.score_order == "max" else -1
            ranked = sorted(
                self.records,
                key=lambda r: sign * float(r["metrics"].get(self.score_attribute, 0.0)),
                reverse=True)
            keep = ranked[: self.num_to_keep]
        else:
            keep = self.records[-self.num_to_keep:]
        for rec in self.records:
            if rec not in keep:
                shutil.rmtree(rec["path"], ignore_errors=True)
        self.records = [r for r in self.records if r in keep]

    def latest(self) -> Optional[Checkpoint]:
        return Checkpoint(self.records[-1]["path"]) if self.records else None

    def best(self) -> Optional[Checkpoint]:
        if not self.records:
            return None
        if not self.score_attribute:
            return self.latest()
        sign = 1 if self.score_order == "max" else -1
        rec = max(self.records,
                  key=lambda r: sign * float(r["metrics"].get(self.score_attribute, 0.0)))
        return Checkpoint(rec["path"])

"""Train checkpoints: directory handles over the checkpoint plane.

Reference: ray.train.Checkpoint (directory handle) and ``CheckpointManager``
(train/v2/_internal/execution/checkpoint/checkpoint_manager.py:71).

Since PR 4 the manager is a thin policy layer over ``ray_tpu/ckpt/`` — the
single checkpoint backend: a reported checkpoint directory is snapshotted
as a tree of file-bytes leaves and committed as an immutable manifest +
content-addressed chunks (``<run_dir>/ckpts/``). Consecutive checkpoints
whose files did not change dedup to the same chunks, a torn save is never
visible, and ``latest()/best()`` materialize a directory back out of the
manifest on demand. There is no whole-tree pickle (or ``copytree``) save
path left here.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional


class Checkpoint:
    """A directory of checkpoint artifacts."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: str) -> str:
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"


# ---------------------------------------------------------------------------
# directory <-> tree codec (files as uint8 leaves on the ckpt plane)
# ---------------------------------------------------------------------------


def dir_to_tree(path: str) -> Dict[str, Any]:
    """A checkpoint directory as a flat ``{relpath: uint8 array}`` tree —
    the shape the checkpoint plane stores. File bytes are read into RAM
    here (the snapshot barrier), so the source directory may be deleted
    the moment this returns."""
    import numpy as np

    out: Dict[str, Any] = {}
    root = os.path.abspath(path)
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            with open(full, "rb") as f:
                out[rel] = np.frombuffer(f.read(), dtype=np.uint8)
    return out


def tree_to_dir(tree: Dict[str, Any], dest: str) -> str:
    """Materialize a file tree restored from a manifest back into a
    directory (each file lands atomically)."""
    from ray_tpu.ckpt.manifest import atomic_write

    os.makedirs(dest, exist_ok=True)
    for rel, data in tree.items():
        atomic_write(os.path.join(dest, rel), bytes(memoryview(data)))
    return dest


def checkpoint_store(run_dir: str):
    """The run's checkpoint-plane store (shared by workers + controller).

    Local-only by default. It becomes a :class:`~ray_tpu.ckpt.TieredStore`
    when the run already carries a ``TIER`` descriptor (resuming a tiered
    run re-attaches its backend) or when ``ckpt_tier_root`` (env
    ``RAY_TPU_CKPT_TIER_ROOT``) names a bucket root — each run then
    mirrors asynchronously into ``<tier_root>/<run_name>/`` and restores
    read through the tiers, so a host that lost its local pool (or a
    replacement host) still restores."""
    from ray_tpu._private.config import RAY_CONFIG
    from ray_tpu.ckpt import CheckpointStore
    from ray_tpu.ckpt.tier.tiered import TIER_FILE

    root = os.path.join(run_dir, "ckpts")
    name = os.path.basename(os.path.abspath(run_dir)) or "train"
    if os.path.exists(os.path.join(root, TIER_FILE)):
        from ray_tpu.ckpt import TieredStore

        return TieredStore(root, name=name)
    tier_root = RAY_CONFIG.ckpt_tier_root
    if tier_root:
        from ray_tpu.ckpt import (BucketBackend, DirBucketClient,
                                  TieredStore)

        client = DirBucketClient(os.path.join(tier_root, name))
        return TieredStore(root, name=name, backend=BucketBackend(client))
    return CheckpointStore(root, name=name)


class CheckpointManager:
    """Tracks reported checkpoints, keeps top-K by the configured score
    attribute. Storage is the checkpoint plane; records reference manifest
    ids and directories are materialized lazily on access."""

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self.index = 0
        self.records: List[Dict[str, Any]] = []
        # manifests that never committed (saver crashed mid-write): cached
        # so every latest() after the first does not re-pay the wait
        self._failed_ids: set = set()
        os.makedirs(run_dir, exist_ok=True)
        self.store = checkpoint_store(run_dir)
        self._load_state()

    def _state_path(self) -> str:
        return os.path.join(self.run_dir, "checkpoint_manager.json")

    def _load_state(self):
        try:
            with open(self._state_path()) as f:
                state = json.load(f)
            self.index = state["index"]
            self.records = [self._migrate_record(i, r)
                            for i, r in enumerate(state["records"])]
        except (FileNotFoundError, json.JSONDecodeError, KeyError):
            pass

    @staticmethod
    def _migrate_record(i: int, rec: Dict[str, Any]) -> Dict[str, Any]:
        """Accept pre-plane records ({"path": dir, ...}): they have no
        manifest (ckpt_id None) and resolve straight to their directory —
        a run started on the previous layout resumes instead of crashing."""
        if "ckpt_id" in rec:
            return rec
        path = rec.get("path", "")
        try:
            index = int(os.path.basename(path).rsplit("_", 1)[-1])
        except (ValueError, IndexError):
            index = i + 1
        return {"ckpt_id": None, "index": index, "path": path,
                "metrics": rec.get("metrics", {}), "time": rec.get("time", 0)}

    def _save_state(self):
        from ray_tpu.ckpt.manifest import atomic_write

        atomic_write(self._state_path(),
                     json.dumps({"index": self.index,
                                 "records": self.records}).encode())

    # -- registration --------------------------------------------------

    def register(self, source_dir: str, metrics: Dict[str, Any]) -> Checkpoint:
        """Persist a reported checkpoint directory through the plane
        (blocking — used by callers that hand over a directory they are
        about to delete)."""
        from ray_tpu.ckpt import save_checkpoint

        manifest = save_checkpoint(self.store, dir_to_tree(source_dir),
                                   step=self.index + 1, metrics=metrics)
        return self.register_manifest(manifest.ckpt_id, metrics)

    def register_manifest(self, ckpt_id: str,
                          metrics: Dict[str, Any]) -> Checkpoint:
        """Record an already-saved (possibly still committing) checkpoint
        manifest — the worker-side async save path."""
        self.index += 1
        self.records.append({"ckpt_id": ckpt_id, "index": self.index,
                             "metrics": metrics, "time": time.time()})
        self._prune()
        self._save_state()
        return Checkpoint(self._dir_for(self.records[-1]))

    # -- retention -----------------------------------------------------

    def _ranked(self) -> List[Dict[str, Any]]:
        if not self.score_attribute:
            return list(self.records)
        sign = 1 if self.score_order == "max" else -1
        return sorted(
            self.records,
            key=lambda r: sign * float(
                r["metrics"].get(self.score_attribute, 0.0)),
            reverse=True)

    def _prune(self):
        if self.num_to_keep is None or len(self.records) <= self.num_to_keep:
            return
        if self.score_attribute:
            keep = self._ranked()[: self.num_to_keep]
        else:
            keep = self.records[-self.num_to_keep:]
        for rec in self.records:
            if rec not in keep:
                shutil.rmtree(self._dir_for(rec), ignore_errors=True)
        self.records = [r for r in self.records if r in keep]
        # drop the superseded manifests and GC their now-orphan chunks;
        # the store's grace window protects chunks of a save whose
        # manifest has not committed yet (the worker-side async path)
        self.store.retention(keep_last=0,
                             keep_ids=[r["ckpt_id"] for r in self.records
                                       if r.get("ckpt_id")])

    # -- access --------------------------------------------------------

    def _dir_for(self, rec: Dict[str, Any]) -> str:
        return rec.get("path") or os.path.join(
            self.run_dir, f"checkpoint_{rec['index']:06d}")

    def _materialize(self, rec: Dict[str, Any],
                     timeout: float = 10.0) -> Optional[str]:
        """Directory for a record, restored from its manifest on first
        access. Returns None when the manifest never committed (saver
        died mid-write) — callers fall back to the previous record."""
        dest = self._dir_for(rec)
        if os.path.isdir(dest):
            return dest
        if rec.get("ckpt_id") is None:  # pre-plane record, dir is gone
            return None
        if rec["ckpt_id"] in self._failed_ids:
            return None
        from ray_tpu.ckpt import restore_tree

        try:
            self.store.wait_for(rec["ckpt_id"], timeout=timeout)
            tree = restore_tree(self.store, rec["ckpt_id"])
        except (TimeoutError, FileNotFoundError, KeyError, ValueError):
            # blacklist only once the record is old enough that its save
            # can no longer be in flight — a merely-slow commit must not
            # be skipped forever, a truly torn one must only be waited
            # for once
            if time.time() - rec.get("time", 0) > 60.0:
                self._failed_ids.add(rec["ckpt_id"])
            return None
        return tree_to_dir(tree, dest)

    def latest(self) -> Optional[Checkpoint]:
        """Newest restorable checkpoint: records whose manifest never
        committed (a save torn by a crash) are skipped, newest-first."""
        for i, rec in enumerate(reversed(self.records)):
            # only the newest record may still be mid-commit; give it a
            # short grace window, fall straight through for older ones
            path = self._materialize(rec, timeout=10.0 if i == 0 else 0.0)
            if path is not None:
                return Checkpoint(path)
        return None

    def best(self) -> Optional[Checkpoint]:
        if not self.records:
            return None
        if not self.score_attribute:
            return self.latest()
        for rec in self._ranked():
            path = self._materialize(rec)
            if path is not None:
                return Checkpoint(path)
        return None

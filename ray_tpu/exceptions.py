"""Public exception types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised; re-raised at `get` with the remote traceback attached.

    Reference: RayTaskError in python/ray/exceptions.py.
    """

    def __init__(self, cause_repr: str, traceback_str: str = "", cause: BaseException | None = None):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(cause_repr)

    def __str__(self):
        if self.traceback_str:
            return f"{self.cause_repr}\n\nremote traceback:\n{self.traceback_str}"
        return self.cause_repr


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly (e.g. OOM-killed)."""


class OutOfMemoryError(TaskError):
    """The memory monitor killed the worker running this task (reference:
    ray.exceptions.OutOfMemoryError; raylet worker_killing_policy)."""


class StrayInterrupt(RayTpuError):
    """Marker cause: a cancellation async-exc landed in the wrong task on
    a shared executor thread; the interrupted task is retried."""


class TaskCancelledError(TaskError):
    """The task was cancelled via ray_tpu.cancel (reference:
    ray.exceptions.TaskCancelledError). Default-constructible: cooperative
    cancellation raises this CLASS into the running task's thread."""

    def __init__(self, cause_repr: str = "TaskCancelledError: task was cancelled",
                 traceback_str: str = "", cause: BaseException | None = None):
        super().__init__(cause_repr, traceback_str, cause)


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is permanently dead (creation failed, killed, or out of restarts)."""


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting); the call may be retried."""


class ObjectLostError(RayTpuError):
    """All copies of the object were lost and lineage reconstruction failed."""


class OwnerDiedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass

"""Serve-LLM: LLMServer deployments + OpenAI-style app builder.

Reference: llm/_internal/serve/core/server/llm_server.py (LLMServer
deployment wrapping an engine), build_openai_app (OpenAI-compatible
ingress). Each replica owns one ``JaxLLMEngine``; requests are enqueued to
the engine and a single pump task drives ``engine.step()`` while anything is
unfinished, so concurrent requests continuously batch on the TPU.

Prefix-aware routing (reference: routing_policies/prefix_aware/): the
``LLMHandle`` hashes a prompt prefix to prefer a consistent replica, which
keeps likely-shared KV prefixes on the same engine.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.llm.config import LLMConfig, SamplingParams
from ray_tpu.serve import api as serve_api


class LLMServer:
    """Deployment callable owning one engine (reference: llm_server.py)."""

    def __init__(self, config: LLMConfig, params_blob: Optional[bytes] = None):
        from ray_tpu.llm.engine import JaxLLMEngine

        params = None
        if params_blob is not None:
            # driver-authored params blob: deserialize only through the
            # audited serialization boundary (raylint SER001)
            from ray_tpu._private.serialization import loads_trusted

            params = loads_trusted(params_blob)
        self.config = config
        self.engine = JaxLLMEngine(config, params=params)
        self._futures: Dict[str, asyncio.Future] = {}
        self._pump_task: Optional[asyncio.Task] = None

    async def _pump(self):
        loop = asyncio.get_event_loop()
        try:
            while self.engine.has_unfinished():
                outputs = await loop.run_in_executor(None, self.engine.step)
                for out in outputs:
                    if out.finished and out.request_id in self._futures:
                        fut = self._futures.pop(out.request_id)
                        if not fut.done():
                            toks = [t for t in out.token_ids
                                    if t != self.engine.tokenizer.eos_token_id]
                            fut.set_result(
                                {"token_ids": out.token_ids,
                                 "text": self.engine.tokenizer.decode(toks),
                                 "finish_reason": out.finish_reason})
                await asyncio.sleep(0)
        except Exception as e:
            # fail every pending request rather than hanging its caller
            for rid, fut in list(self._futures.items()):
                if not fut.done():
                    fut.set_exception(RuntimeError(f"engine step failed: {e}"))
                self.engine.abort_request(rid)
            self._futures.clear()
            raise
        finally:
            self._pump_task = None

    async def _submit(self, prompt: Any, params: SamplingParams) -> dict:
        rid = uuid.uuid4().hex
        fut = asyncio.get_event_loop().create_future()
        self._futures[rid] = fut
        self.engine.add_request(rid, prompt, params)
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.ensure_future(self._pump())
        return await fut

    async def completions(self, prompt: str, *, max_tokens: int = 64,
                          temperature: float = 0.0, top_k: int = 0,
                          top_p: float = 1.0) -> dict:
        params = SamplingParams(max_tokens=max_tokens, temperature=temperature,
                                top_k=top_k, top_p=top_p)
        return await self._submit(prompt, params)

    async def chat(self, messages: List[dict], **kw) -> dict:
        prompt = "".join(
            f"<{m.get('role', 'user')}>{m.get('content', '')}" for m in messages
        ) + "<assistant>"
        return await self.completions(prompt, **kw)

    async def __call__(self, body: dict) -> dict:
        """OpenAI-ish JSON entry point (used by the HTTP proxy)."""
        kw = {k: body[k] for k in ("max_tokens", "temperature", "top_k", "top_p")
              if k in body}
        if "messages" in body:
            out = await self.chat(body["messages"], **kw)
            return {"id": uuid.uuid4().hex, "object": "chat.completion",
                    "choices": [{"index": 0,
                                 "message": {"role": "assistant",
                                             "content": out["text"]},
                                 "finish_reason": out["finish_reason"]}]}
        out = await self.completions(body.get("prompt", ""), **kw)
        return {"id": uuid.uuid4().hex, "object": "text_completion",
                "choices": [{"index": 0, "text": out["text"],
                             "finish_reason": out["finish_reason"]}]}

    async def update_weights(self, store_name: str,
                             version: Optional[int] = None) -> dict:
        """Live weight update from the weight plane: pull ``version``
        (default: newest) from the named WeightStore and swap engine params
        between steps. In-flight requests keep decoding — the swap is one
        attribute assignment on the pump's thread boundary, so no request
        is dropped or restarted. Rolled out across replicas with
        ``handle.broadcast("update_weights", store_name)``."""
        loop = asyncio.get_event_loop()

        def _pull():
            from ray_tpu.weights import WeightStore

            return WeightStore(store_name).pull(version, return_version=True)

        tree, ver = await loop.run_in_executor(None, _pull)
        self.engine.params = tree
        return {"version": ver, "model_id": self.config.model_id}

    async def save_engine_state(self, path: str, *, step: int = 0) -> dict:
        """Checkpoint the engine params through the checkpoint plane
        (``ray_tpu/ckpt``): ``path`` becomes a manifest + chunk store, so
        rolling saves across replicas dedup identical params to the same
        chunks. Runs off-loop — in-flight requests keep decoding."""
        loop = asyncio.get_event_loop()

        def _save():
            from ray_tpu.llm.engine import save_params

            return save_params(self.engine.params, path, step=step)

        manifest_path = await loop.run_in_executor(None, _save)
        return {"manifest": manifest_path, "model_id": self.config.model_id}

    async def load_engine_state(self, path: str) -> dict:
        """Swap engine params from a checkpoint-plane store (or legacy
        msgpack dir); the swap is one attribute assignment between steps,
        like ``update_weights``."""
        loop = asyncio.get_event_loop()

        def _load():
            from ray_tpu.llm.engine import _load_params

            return _load_params(path)

        self.engine.params = await loop.run_in_executor(None, _load)
        return {"model_id": self.config.model_id, "source": path}

    def engine_metrics(self) -> dict:
        return dict(self.engine.metrics)


def build_llm_deployment(config: LLMConfig, params: Any = None,
                         name: Optional[str] = None) -> serve_api.Application:
    """Deployment app for one LLMConfig (reference: build_llm_deployment)."""
    opts = dict(config.ray_actor_options) or {"num_cpus": 1.0}
    params_blob = None
    if params is not None:
        import cloudpickle

        params_blob = cloudpickle.dumps(params)
    dep = serve_api.deployment(
        LLMServer, name=name or f"llm:{config.model_id}",
        num_replicas=config.num_replicas,
        max_ongoing_requests=config.engine_config.max_num_seqs * 2,
        ray_actor_options=opts)
    return dep.bind(config, params_blob)


def build_openai_app(configs: List[LLMConfig], params: Any = None
                     ) -> Dict[str, serve_api.DeploymentHandle]:
    """Deploy one LLMServer per config; returns name->handle (the HTTP proxy
    then serves POST /<name> with OpenAI-style bodies)."""
    handles = {}
    for cfg in configs:
        app = build_llm_deployment(cfg, params=params)
        handles[app.deployment.name] = serve_api.run(app)
    return handles


class LLMHandle:
    """Prefix-aware handle: same prompt prefix -> same replica when healthy,
    keeping likely-shared KV prefixes on one engine. Thin veneer over the
    first-class ``routing_policy="prefix"`` handle policy
    (ray_tpu/serve/autoscale/router.py — consistent-hash ring, so replica
    churn remaps only ~1/N of the prefix space; hit/miss counters land on
    ``ray_tpu.serve.prefix_cache_*``)."""

    def __init__(self, deployment_name: str, prefix_len: int = 64):
        self._inner = serve_api.DeploymentHandle(
            deployment_name, routing_policy="prefix")
        self._inner._router().prefix_len = prefix_len

    def remote(self, body: dict):
        return self._inner.remote(body)

"""Data-LLM: batch inference processors over Datasets.

Reference: ray.data.llm build_llm_processor
(llm/_internal/batch/processor/vllm_engine_proc.py + data/llm.py) — a
processor maps a Dataset of prompts through a shared engine with
preprocess/postprocess stages. Here the engine lives in one detached actor
per processor (an engine per map-task would re-compile per block); map tasks
route their batch of prompts to it, so blocks from many tasks continuously
batch on the same TPU engine.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.llm.config import LLMConfig, SamplingParams


@ray_tpu.remote
class _EngineActor:
    def __init__(self, config_blob: bytes, params_blob: Optional[bytes]):
        # driver-authored blobs: decode only through the audited
        # serialization boundary (raylint SER001)
        from ray_tpu._private.serialization import loads_trusted

        from ray_tpu.llm.engine import JaxLLMEngine

        config = loads_trusted(config_blob)
        params = loads_trusted(params_blob) if params_blob else None
        self.engine = JaxLLMEngine(config, params=params)

    def generate(self, prompts, params_blob: bytes):
        from ray_tpu._private.serialization import loads_trusted

        params = loads_trusted(params_blob)
        outs = self.engine.generate(list(prompts), params)
        return [{"text": o.text, "token_ids": o.token_ids,
                 "finish_reason": o.finish_reason} for o in outs]


def build_llm_processor(
    config: LLMConfig,
    params: Any = None,
    *,
    sampling_params: Optional[SamplingParams] = None,
    preprocess: Optional[Callable[[dict], str]] = None,
    postprocess: Optional[Callable[[dict, dict], dict]] = None,
) -> Callable:
    """Returns ``processor(dataset) -> dataset`` adding generation columns.

    ``preprocess(row) -> prompt`` defaults to ``row["prompt"]``;
    ``postprocess(row, out) -> row`` defaults to merging ``generated_text``.
    """
    import cloudpickle

    sampling_params = sampling_params or SamplingParams()
    actor_name = f"_llm_proc_{uuid.uuid4().hex[:8]}"
    cfg_blob = cloudpickle.dumps(config)
    p_blob = cloudpickle.dumps(params) if params is not None else None
    opts = dict(config.ray_actor_options) or {"num_cpus": 1.0}
    # named but NOT detached: the engine actor dies with the driver job, so
    # an abandoned processor can't pin a TPU forever
    engine = _EngineActor.options(
        name=actor_name, get_if_exists=True,
        num_cpus=opts.get("num_cpus", 1.0),
        num_tpus=opts.get("num_tpus", 0.0)).remote(cfg_blob, p_blob)
    sp_blob = cloudpickle.dumps(sampling_params)

    def processor(dataset):
        def _infer_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
            import numpy as np

            import ray_tpu as _rt

            eng = _rt.get_actor(actor_name)
            keys = list(batch.keys())
            n = len(batch[keys[0]]) if keys else 0
            rows = [{k: batch[k][i] for k in keys} for i in range(n)]
            if preprocess is not None:
                prompts = [preprocess(r) for r in rows]
            else:
                prompts = [str(r.get("prompt", "")) for r in rows]
            outs = _rt.get(eng.generate.remote(prompts, sp_blob),
                           timeout=600)
            out_rows = []
            for r, o in zip(rows, outs):
                if postprocess is not None:
                    out_rows.append(postprocess(r, o))
                else:
                    r = dict(r)
                    r["generated_text"] = o["text"]
                    out_rows.append(r)
            cols = {k: np.array([row[k] for row in out_rows], dtype=object)
                    for k in out_rows[0]} if out_rows else {}
            return cols

        return dataset.map_batches(_infer_batch)

    processor.engine_actor = engine  # keepalive + test access
    processor.shutdown = lambda: ray_tpu.kill(engine)
    return processor

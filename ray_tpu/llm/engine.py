"""Continuous-batching JAX LLM engine (TPU-native vLLM-engine equivalent).

Reference capability: ray.llm wraps vLLM's AsyncLLMEngine
(llm/_internal/serve/engines/vllm/vllm_engine.py) — request queue, paged KV
cache, continuous batching. Here the engine is a host-side scheduler over
two compiled XLA programs (prefill per shape bucket, one decode step):

- slots: ``max_num_seqs`` concurrent sequences, fixed batch shape so decode
  is a single cached compilation;
- pages: a free list of KV pages; sequences allocate pages on demand as they
  cross page boundaries (admission blocks when no pages are free);
- scheduling per ``step()``: admit waiting requests into free slots (batched
  bucketed prefill), then run one decode step for all active slots.

The engine is synchronous and single-threaded by design — actor wrappers
(serve_llm.LLMServer) give it an async front end.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.llm.config import EngineConfig, LLMConfig, SamplingParams
from ray_tpu.llm.tokenizer import get_tokenizer


@dataclasses.dataclass
class _Request:
    request_id: str
    prompt_tokens: List[int]  # original prompt (never mutated)
    params: SamplingParams
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    pages: List[int] = dataclasses.field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[str] = None

    @property
    def cache_tokens(self) -> List[int]:
        """Tokens re-prefilled on (re)admission: prompt + anything already
        generated before a preemption (vLLM's recompute preemption, without
        dropping emitted tokens from the output)."""
        return self.prompt_tokens + self.generated


@dataclasses.dataclass
class RequestOutput:
    request_id: str
    token_ids: List[int]
    finished: bool
    finish_reason: Optional[str]
    text: Optional[str] = None


class JaxLLMEngine:
    """Synchronous continuous-batching engine over the paged-KV model runner."""

    def __init__(self, config: LLMConfig, params: Any = None, seed: int = 0):
        import jax

        from ray_tpu.llm import model_runner

        self.config = config
        self.ecfg: EngineConfig = config.engine_config
        self.mcfg = config.transformer_config()
        self.tokenizer = get_tokenizer(config.tokenizer)
        self._mr = model_runner
        self._jax = jax

        if params is not None:
            self.params = params
        elif config.checkpoint_path:
            self.params = _load_params(config.checkpoint_path)
        else:
            self.params = self._init_random_params(seed)

        e = self.ecfg
        self.cache = model_runner.init_cache(self.mcfg, e.num_pages, e.page_size)
        B, MP = e.max_num_seqs, e.pages_per_seq
        self._block_tables = np.zeros((B, MP), np.int32)
        self._seq_lens = np.zeros(B, np.int32)
        self._last_tokens = np.zeros(B, np.int32)
        self._active = np.zeros(B, bool)
        self._temps = np.zeros(B, np.float32)
        self._top_ks = np.zeros(B, np.int32)
        self._top_ps = np.ones(B, np.float32)
        self._seeds = np.full(B, -1, np.int32)  # -1 = engine-global stream
        self._slots: List[Optional[_Request]] = [None] * B
        self._free_pages = collections.deque(range(1, e.num_pages))
        self._waiting: collections.deque[_Request] = collections.deque()
        self._requests: Dict[str, _Request] = {}
        self._rng = jax.random.PRNGKey(seed)
        self.metrics = {"prefill_tokens": 0, "decode_steps": 0,
                        "generated_tokens": 0, "preempted": 0}

    # -- params ------------------------------------------------------------

    def _init_random_params(self, seed: int):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import Transformer

        import flax.linen as nn

        model = Transformer(self.mcfg)
        toks = jnp.zeros((1, min(8, self.mcfg.max_seq_len)), jnp.int32)
        return nn.meta.unbox(model.init(jax.random.PRNGKey(seed), toks))

    # -- request lifecycle -------------------------------------------------

    def add_request(self, request_id: str, prompt: Any,
                    params: Optional[SamplingParams] = None) -> None:
        params = params or SamplingParams()
        if isinstance(prompt, str):
            tokens = self.tokenizer.encode(prompt)
        else:
            tokens = list(prompt)
        limit = self.ecfg.max_model_len - 1
        if len(tokens) > limit:
            tokens = tokens[-limit:]
        # reject requests the page pool can never satisfy (even alone) —
        # otherwise admission would livelock retrying forever
        final_len = min(self.ecfg.max_model_len,
                        len(tokens) + params.max_tokens)
        need_total = math.ceil(final_len / self.ecfg.page_size)
        if need_total > self.ecfg.num_pages - 1:
            raise ValueError(
                f"request needs {need_total} KV pages but the engine has "
                f"{self.ecfg.num_pages - 1}; raise num_pages or lower "
                f"max_tokens/prompt length")
        req = _Request(request_id, tokens, params)
        self._requests[request_id] = req
        self._waiting.append(req)

    def abort_request(self, request_id: str) -> None:
        req = self._requests.pop(request_id, None)
        if req is None:
            return
        if req.slot >= 0:
            self._release(req)
        else:
            try:
                self._waiting.remove(req)
            except ValueError:
                pass

    def has_unfinished(self) -> bool:
        return bool(self._waiting) or self._active.any()

    def num_waiting(self) -> int:
        return len(self._waiting)

    def num_active(self) -> int:
        return int(self._active.sum())

    # -- scheduling internals ----------------------------------------------

    def _release(self, req: _Request) -> None:
        self._free_pages.extend(req.pages)
        req.pages = []
        if req.slot >= 0:
            self._active[req.slot] = False
            self._slots[req.slot] = None
            self._seq_lens[req.slot] = 0
            self._block_tables[req.slot, :] = 0
            req.slot = -1

    def _try_admit(self) -> List[_Request]:
        admitted = []
        free_slots = [i for i, s in enumerate(self._slots) if s is None]
        while self._waiting and free_slots:
            req = self._waiting[0]
            need = max(1, math.ceil(len(req.cache_tokens)
                                    / self.ecfg.page_size))
            if len(self._free_pages) < need:
                break
            self._waiting.popleft()
            req.slot = free_slots.pop(0)
            req.pages = [self._free_pages.popleft() for _ in range(need)]
            self._slots[req.slot] = req
            row = self._block_tables[req.slot]
            row[:] = 0
            row[:need] = req.pages
            self._seq_lens[req.slot] = len(req.cache_tokens)
            p = req.params
            self._temps[req.slot] = p.temperature
            self._top_ks[req.slot] = p.top_k
            self._top_ps[req.slot] = p.top_p
            self._seeds[req.slot] = -1 if p.seed is None else p.seed
            admitted.append(req)
        return admitted

    def _prefill_bucket(self, n: int) -> int:
        b = self.ecfg.prefill_bucket_min
        while b < n:
            b *= 2
        return min(b, self.ecfg.max_model_len)

    def _ensure_page(self, req: _Request) -> bool:
        """Allocate the page for the next token position if needed."""
        pos = int(self._seq_lens[req.slot])
        need = pos // self.ecfg.page_size + 1
        if need <= len(req.pages):
            return True
        if not self._free_pages:
            return False
        page = self._free_pages.popleft()
        req.pages.append(page)
        self._block_tables[req.slot, need - 1] = page
        return True

    def _next_rng(self):
        self._rng, sub = self._jax.random.split(self._rng)
        return sub

    def _sample(self, logits) -> np.ndarray:
        import jax.numpy as jnp

        steps = np.array(
            [len(s.generated) if s is not None else 0 for s in self._slots],
            np.int32)
        toks = self._mr.sample_tokens(
            logits, self._next_rng(), jnp.asarray(self._temps),
            jnp.asarray(self._top_ks), jnp.asarray(self._top_ps),
            jnp.asarray(self._seeds), jnp.asarray(steps),
            max_top_k=self.ecfg.max_top_k)
        return np.asarray(toks)

    # -- the step ----------------------------------------------------------

    def step(self, decode: bool = True) -> List[RequestOutput]:
        """One scheduling step. ``decode=False`` runs only the admit+prefill
        phase — the prefill side of PD disaggregation (reference serving
        pattern: serving_patterns/prefill_decode/pd_server.py:31)."""
        import jax.numpy as jnp

        outputs: List[RequestOutput] = []
        e, mr = self.ecfg, self._mr
        B = e.max_num_seqs

        # 1) admit + batched prefill (one bucketed program, full-B batch)
        admitted = self._try_admit()
        if admitted:
            max_len = max(len(r.cache_tokens) for r in admitted)
            S = self._prefill_bucket(max_len)
            toks = np.zeros((B, S), np.int32)
            lens = np.zeros(B, np.int32)
            for r in admitted:
                full = r.cache_tokens
                toks[r.slot, :len(full)] = full
                lens[r.slot] = len(full)
            logits, self.cache = mr.prefill(
                self.params, self.mcfg, self.cache, jnp.asarray(toks),
                jnp.asarray(lens), jnp.asarray(self._block_tables))
            toks_np = self._sample(logits)
            self.metrics["prefill_tokens"] += int(lens.sum())
            for r in admitted:
                self._active[r.slot] = True
                self._emit(r, int(toks_np[r.slot]), outputs)

        # 2) one decode step for all active slots
        if decode and self._active.any():
            # page-boundary allocation; preempt to waiting on exhaustion
            for req in [s for s in self._slots if s is not None]:
                if self._active[req.slot] and not self._ensure_page(req):
                    self.metrics["preempted"] += 1
                    self._requeue(req)
            if self._active.any():
                logits, self.cache = mr.decode_step(
                    self.params, self.mcfg, self.cache,
                    jnp.asarray(self._last_tokens), jnp.asarray(self._seq_lens),
                    jnp.asarray(self._block_tables), jnp.asarray(self._active))
                toks_np = self._sample(logits)
                self.metrics["decode_steps"] += 1
                for req in list(self._slots):
                    if req is not None and self._active[req.slot]:
                        self._seq_lens[req.slot] += 1
                        self._emit(req, int(toks_np[req.slot]), outputs)
        return outputs

    def _requeue(self, req: _Request) -> None:
        """Preempt a running request back to the waiting queue; its KV is
        recomputed from prompt+generated on re-admission (vLLM's recompute
        preemption). ``generated`` is kept so emitted tokens and the
        max_tokens budget survive preemption."""
        self._release(req)
        self._waiting.appendleft(req)

    def _emit(self, req: _Request, token: int, outputs: List[RequestOutput]):
        req.generated.append(token)
        self._last_tokens[req.slot] = token
        self.metrics["generated_tokens"] += 1
        eos = self.tokenizer.eos_token_id
        total = len(req.prompt_tokens) + len(req.generated)
        if token == eos or token in req.params.stop_token_ids:
            req.finished, req.finish_reason = True, "stop"
        elif len(req.generated) >= req.params.max_tokens:
            req.finished, req.finish_reason = True, "length"
        elif total >= self.ecfg.max_model_len:
            req.finished, req.finish_reason = True, "length"
        if req.finished:
            self._release(req)
            self._requests.pop(req.request_id, None)
        outputs.append(RequestOutput(
            req.request_id, list(req.generated), req.finished,
            req.finish_reason))

    # -- PD disaggregation (KV page export / import) -----------------------
    # Reference: serving_patterns/prefill_decode/pd_server.py + the vLLM
    # KV-transfer connectors (engines/vllm/kv_transfer/). The paged layout
    # makes a sequence's KV state a gather of its pages.

    def prefill_only(self, request_id: str, prompt: Any,
                     params: Optional[SamplingParams] = None,
                     max_steps: int = 1000) -> dict:
        """Prefill one request (emitting its first token) and export its KV
        state; the request is then released here — a decode engine imports
        the state and continues without re-prefilling."""
        self.add_request(request_id, prompt, params)
        req = self._requests[request_id]
        for _ in range(max_steps):
            self.step(decode=False)
            if req.finished or req.generated:
                break
        else:
            self.abort_request(request_id)
            raise RuntimeError(f"prefill of {request_id} did not get admitted")
        if req.finished:
            # done at prefill (e.g. max_tokens=1): no KV to hand off
            return {"request_id": request_id,
                    "prompt_tokens": list(req.prompt_tokens),
                    "generated": list(req.generated), "seq_len": 0,
                    "finished": True, "finish_reason": req.finish_reason,
                    "params": req.params}
        return self.export_kv(request_id)

    def export_kv(self, request_id: str) -> dict:
        """Gather a live request's KV pages + scheduling state, releasing
        the request locally. The blob is plain numpy: it ships over the
        object plane (or the device-object plane when replicas colocate)."""
        req = self._requests.get(request_id)
        if req is None or req.slot < 0:
            raise KeyError(f"no live request {request_id}")
        pages = np.asarray(req.pages, np.int32)
        state = {
            "request_id": req.request_id,
            "prompt_tokens": list(req.prompt_tokens),
            "generated": list(req.generated),
            "seq_len": int(self._seq_lens[req.slot]),
            "finished": req.finished,
            "finish_reason": req.finish_reason,
            "params": req.params,
            "k": np.asarray(self.cache.k[:, pages]),
            "v": np.asarray(self.cache.v[:, pages]),
        }
        self.abort_request(request_id)
        return state

    def add_request_with_kv(self, state: dict) -> None:
        """Admit a prefilled request directly into a decode slot: allocate
        fresh pages, scatter the imported KV into them, and resume decoding
        at the imported position (no re-prefill)."""
        import jax.numpy as jnp

        if state.get("finished"):
            # finished during prefill (e.g. max_tokens=1): nothing to decode
            raise ValueError("request already finished at prefill")
        free_slots = [i for i, s in enumerate(self._slots) if s is None]
        n_pages = state["k"].shape[1]
        if not free_slots or len(self._free_pages) < n_pages:
            raise RuntimeError("decode engine has no capacity; retry")
        req = _Request(state["request_id"], list(state["prompt_tokens"]),
                       state["params"])
        req.generated = list(state["generated"])
        req.slot = free_slots[0]
        req.pages = [self._free_pages.popleft() for _ in range(n_pages)]
        pages = jnp.asarray(np.asarray(req.pages, np.int32))
        self.cache = self._mr.KVCache(
            self.cache.k.at[:, pages].set(jnp.asarray(state["k"])),
            self.cache.v.at[:, pages].set(jnp.asarray(state["v"])))
        row = self._block_tables[req.slot]
        row[:] = 0
        row[:n_pages] = req.pages
        self._seq_lens[req.slot] = state["seq_len"]
        self._last_tokens[req.slot] = req.generated[-1]
        p = req.params
        self._temps[req.slot] = p.temperature
        self._top_ks[req.slot] = p.top_k
        self._top_ps[req.slot] = p.top_p
        self._seeds[req.slot] = -1 if p.seed is None else p.seed
        self._slots[req.slot] = req
        self._active[req.slot] = True
        self._requests[req.request_id] = req

    # -- convenience -------------------------------------------------------

    def generate(self, prompts: List[Any],
                 params: Optional[SamplingParams] = None,
                 decode_text: bool = True) -> List[RequestOutput]:
        """Blocking batch generation; preserves input order."""
        ids = [f"gen-{i}-{time.monotonic_ns()}" for i in range(len(prompts))]
        for rid, prompt in zip(ids, prompts):
            self.add_request(rid, prompt, params)
        done: Dict[str, RequestOutput] = {}
        while self.has_unfinished():
            for out in self.step():
                if out.finished:
                    done[out.request_id] = out
        results = [done[rid] for rid in ids]
        if decode_text:
            for r in results:
                toks = [t for t in r.token_ids
                        if t != self.tokenizer.eos_token_id]
                r.text = self.tokenizer.decode(toks)
        return results


def _load_params(path: str):
    """Engine params from ``path``: a checkpoint-plane store (manifest +
    content-addressed chunks — the format ``save_params`` writes), or the
    legacy single-file ``params.msgpack`` layout."""
    import os

    from ray_tpu.ckpt import CheckpointStore, restore_tree

    if os.path.isdir(path):
        store = CheckpointStore(path, name="llm")
        if store.latest_id() is not None:
            return restore_tree(store)

    import flax.serialization

    fn = path if os.path.isfile(path) else os.path.join(path, "params.msgpack")
    with open(fn, "rb") as f:
        blob = f.read()
    return flax.serialization.msgpack_restore(blob)


def save_params(params: Any, path: str, *, step: int = 0) -> str:
    """Commit engine params through the checkpoint plane: ``path`` becomes
    a checkpoint store (manifest + chunks). Repeated saves of mostly-
    unchanged params (a LoRA refresh, an embedding-only update) dedup to
    the shared chunk pool; a torn save never becomes ``latest``."""
    import os

    import flax.serialization

    from ray_tpu.ckpt import CheckpointStore, save_checkpoint

    state = flax.serialization.to_state_dict(params)
    store = CheckpointStore(path, name="llm")
    manifest = save_checkpoint(store, state, step=step)
    return os.path.join(path, "manifests", f"{manifest.ckpt_id}.json")

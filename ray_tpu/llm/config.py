"""LLM configs (reference: llm/_internal/serve/core/configs/llm_config.py:141).

``LLMConfig`` describes one deployable model: which transformer config to
instantiate (or checkpoint to load), the engine's batching/cache geometry,
and serve-level options. ``SamplingParams`` mirrors the per-request options
(reference: vLLM SamplingParams surfaced through ray.serve.llm).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0  # 0 = no top-k restriction
    top_p: float = 1.0
    stop_token_ids: tuple = ()
    seed: Optional[int] = None

    def __post_init__(self):
        if self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")


@dataclass
class EngineConfig:
    """Cache/batching geometry of the JAX engine.

    The paged KV cache holds ``num_pages`` pages of ``page_size`` tokens per
    layer; a sequence owns ceil(len/page_size) pages recorded in its block
    table (vLLM's PagedAttention layout, re-done as fixed-shape jnp arrays so
    every decode step hits one compiled XLA program).
    """

    max_num_seqs: int = 8           # concurrent decode slots (batch size)
    max_model_len: int = 2048       # prompt + generation cap per sequence
    page_size: int = 16             # tokens per KV page
    num_pages: Optional[int] = None  # default: enough for all slots + scratch
    max_top_k: int = 64             # static top-k width compiled into sampler
    prefill_bucket_min: int = 32    # pad prompts up to pow2 buckets >= this

    def __post_init__(self):
        if self.max_model_len % self.page_size:
            raise ValueError("max_model_len must be a multiple of page_size")
        if self.num_pages is None:
            # one scratch page (index 0) absorbs masked-out writes
            self.num_pages = 1 + self.max_num_seqs * self.pages_per_seq

    @property
    def pages_per_seq(self) -> int:
        return self.max_model_len // self.page_size


@dataclass
class LLMConfig:
    """One deployable LLM (reference: llm_config.py:141 model_loading_config
    + engine_kwargs + deployment_config)."""

    model_id: str = "tiny"           # key into models.transformer.CONFIGS
    checkpoint_path: Optional[str] = None  # msgpack params (orbax/flax) dir
    tokenizer: str = "byte"          # "byte" or a HF tokenizer name
    engine_config: EngineConfig = field(default_factory=EngineConfig)
    # serve-level
    num_replicas: int = 1
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    # forwarded to TransformerConfig (e.g. attention_impl for CI)
    model_overrides: Dict[str, Any] = field(default_factory=dict)

    def transformer_config(self):
        import dataclasses as _dc

        from ray_tpu.models.transformer import CONFIGS

        cfg = CONFIGS[self.model_id]
        if self.model_overrides:
            cfg = _dc.replace(cfg, **self.model_overrides)
        return cfg

"""LLM serving patterns: prefill/decode disaggregation + data-parallel.

Reference: llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py:31
(prefill replicas hand KV state to decode replicas through a KV-transfer
connector) and serving_patterns/data_parallel/{dp_server.py:14,
dp_rank_assigner.py} (engine replicas coordinate ranks, the router spreads
load across them).

TPU-native shape: the engine's paged KV layout makes a sequence's KV state a
serializable gather of pages (engine.export_kv / add_request_with_kv), so
the hand-off rides the regular object plane — or stays device-resident via
the device-object transport when replicas colocate.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.llm.config import LLMConfig, SamplingParams
from ray_tpu.serve import api as serve_api


def _load_params_blob(params_blob):
    if params_blob is None:
        return None
    # driver-authored params blob: decode only through the audited
    # serialization boundary (raylint SER001)
    from ray_tpu._private.serialization import loads_trusted

    return loads_trusted(params_blob)


class PrefillWorker:
    """Actor owning a prefill-only engine (reference: the P side of
    pd_server.py). Prompts run the batched prefill program; the KV state
    leaves immediately, so this engine never decodes and its page pool
    turns over at prompt-ingest rate."""

    def __init__(self, config: LLMConfig, params_blob: Optional[bytes] = None):
        from ray_tpu.llm.engine import JaxLLMEngine

        self.engine = JaxLLMEngine(config, params=_load_params_blob(params_blob))

    @ray_tpu.method(tensor_transport="device")
    def prefill(self, prompt: Any, params: Optional[SamplingParams] = None) -> dict:
        # tensor_transport="device": the KV state STAYS resident in this
        # worker; the reply is a small marker, and the decode worker pulls
        # the state DIRECTLY from here (producer->consumer p2p over the
        # device-object plane — the router never touches the KV bytes;
        # reference: the KV-transfer connectors of pd_server.py)
        rid = uuid.uuid4().hex
        return self.engine.prefill_only(rid, prompt, params)

    def metrics(self) -> dict:
        return dict(self.engine.metrics)


class DecodeWorker:
    """Actor owning a decode engine: imports prefilled KV and streams the
    completion (reference: the D side of pd_server.py)."""

    def __init__(self, config: LLMConfig, params_blob: Optional[bytes] = None):
        from ray_tpu.llm.engine import JaxLLMEngine

        self.engine = JaxLLMEngine(config, params=_load_params_blob(params_blob))

    def decode(self, state: dict) -> dict:
        eng = self.engine
        rid = state["request_id"]
        if state.get("finished"):
            token_ids = list(state["generated"])
            reason = state.get("finish_reason")
        else:
            eng.add_request_with_kv(state)
            token_ids, reason = list(state["generated"]), None
            while True:
                done = None
                for out in eng.step():
                    if out.request_id == rid and out.finished:
                        done = out
                if done is not None:
                    token_ids, reason = done.token_ids, done.finish_reason
                    break
        toks = [t for t in token_ids if t != eng.tokenizer.eos_token_id]
        return {"token_ids": token_ids, "text": eng.tokenizer.decode(toks),
                "finish_reason": reason}

    def metrics(self) -> dict:
        return dict(self.engine.metrics)


class PDServer:
    """Deployment callable routing each completion prefill -> decode
    (reference: pd_server.py's PDProxyServer)."""

    def __init__(self, config: LLMConfig, params_blob: Optional[bytes] = None,
                 num_prefill: int = 1, num_decode: int = 1,
                 actor_options: Optional[dict] = None):
        opts = actor_options or {"num_cpus": 0.5}
        prefill_cls = ray_tpu.remote(**opts)(PrefillWorker)
        decode_cls = ray_tpu.remote(**opts)(DecodeWorker)
        self.prefill_workers = [prefill_cls.remote(config, params_blob)
                                for _ in range(num_prefill)]
        self.decode_workers = [decode_cls.remote(config, params_blob)
                               for _ in range(num_decode)]
        self._rr = 0

    def _pick(self, group: List[Any]):
        self._rr += 1
        return group[self._rr % len(group)]

    async def completions(self, prompt: str, *, max_tokens: int = 64,
                          temperature: float = 0.0, top_k: int = 0,
                          top_p: float = 1.0) -> dict:
        params = SamplingParams(max_tokens=max_tokens, temperature=temperature,
                                top_k=top_k, top_p=top_p)
        # hand the REF (not the value) to decode: the KV rides the device
        # plane prefill-worker -> decode-worker, never through this router
        state_ref = self._pick(self.prefill_workers).prefill.remote(
            prompt, params)
        return await self._pick(self.decode_workers).decode.remote(state_ref)

    async def __call__(self, body: dict) -> dict:
        kw = {k: body[k] for k in ("max_tokens", "temperature", "top_k", "top_p")
              if k in body}
        out = await self.completions(body.get("prompt", ""), **kw)
        return {"id": uuid.uuid4().hex, "object": "text_completion",
                "choices": [{"index": 0, "text": out["text"],
                             "finish_reason": out["finish_reason"]}]}


def build_pd_openai_app(config: LLMConfig, params: Any = None,
                        num_prefill: int = 1, num_decode: int = 1
                        ) -> serve_api.DeploymentHandle:
    """Deploy the PD pattern; returns the handle serving OpenAI-ish bodies."""
    params_blob = None
    if params is not None:
        import cloudpickle

        params_blob = cloudpickle.dumps(params)
    dep = serve_api.deployment(
        PDServer, name=f"llm-pd:{config.model_id}", num_replicas=1,
        max_ongoing_requests=config.engine_config.max_num_seqs * 2,
        ray_actor_options=dict(config.ray_actor_options) or {"num_cpus": 0.5})
    return serve_api.run(dep.bind(config, params_blob, num_prefill, num_decode))


# ---------------------------------------------------------------------------
# data-parallel serving
# ---------------------------------------------------------------------------


class DPRankAssigner:
    """Named actor handing out dense dp ranks to engine replicas
    (reference: dp_rank_assigner.py:14). Ranks are LEASES: replicas renew
    periodically, and a rank whose holder stopped renewing (controller
    replaced the replica, worker died) is evicted so the replacement can
    claim a slot — without this, dp serving cannot survive replica churn."""

    LEASE_TTL_S = 60.0

    def __init__(self, dp_size: int):
        import time as _time

        self.dp_size = dp_size
        self._time = _time
        self._next = 0
        self._ranks: Dict[str, int] = {}
        self._last_seen: Dict[str, float] = {}

    def _evict_expired(self):
        now = self._time.time()
        for rid in [r for r, ts in self._last_seen.items()
                    if now - ts > self.LEASE_TTL_S]:
            self._ranks.pop(rid, None)
            self._last_seen.pop(rid, None)

    def assign(self, replica_id: str) -> int:
        now = self._time.time()
        if replica_id in self._ranks:
            self._last_seen[replica_id] = now
            return self._ranks[replica_id]
        if self._next >= self.dp_size:
            self._evict_expired()
            # restarted/replacement replica re-uses the lowest freed slot
            used = set(self._ranks.values())
            for r in range(self.dp_size):
                if r not in used:
                    self._ranks[replica_id] = r
                    self._last_seen[replica_id] = now
                    return r
            raise RuntimeError(f"all {self.dp_size} dp ranks assigned")
        rank = self._next
        self._next += 1
        self._ranks[replica_id] = rank
        self._last_seen[replica_id] = now
        return rank

    def renew(self, replica_id: str) -> bool:
        if replica_id not in self._ranks:
            return False  # evicted: the replica should re-assign
        self._last_seen[replica_id] = self._time.time()
        return True

    def release(self, replica_id: str) -> None:
        self._ranks.pop(replica_id, None)
        self._last_seen.pop(replica_id, None)

    def ranks(self) -> Dict[str, int]:
        return dict(self._ranks)


class DPLLMServer:
    """LLMServer variant that claims a dp rank at start (reference:
    dp_server.py — rank coordination around SPMD engine replicas).

    Rank leases are time-based, not fenced: a replica that stalls past the
    lease TTL can briefly coexist with its replacement on the same rank
    until its next renew tick observes the eviction and re-assigns. Ranks
    here tag responses and drive engine sharding identity at START; they
    are not a mutual-exclusion token mid-request."""

    def __init__(self, config: LLMConfig, params_blob: Optional[bytes] = None,
                 assigner_name: str = ""):
        from ray_tpu.llm.serve_llm import LLMServer

        self._inner = LLMServer(config, params_blob)
        self.replica_id = uuid.uuid4().hex
        self.dp_rank = -1
        self._stopped = False
        self._assigner_name = assigner_name
        if assigner_name:
            assigner = ray_tpu.get_actor(assigner_name)
            self.dp_rank = ray_tpu.get(
                assigner.assign.remote(self.replica_id), timeout=60)
            # keep the rank lease alive (a dead replica's lease expires and
            # its slot is recycled for the controller's replacement)
            import threading

            def _renew_loop():
                while not getattr(self, "_stopped", False):
                    time.sleep(DPRankAssigner.LEASE_TTL_S / 4)
                    try:
                        ok = ray_tpu.get(
                            assigner.renew.remote(self.replica_id),
                            timeout=30)
                        if not ok:
                            # evicted while we were unreachable: re-assign
                            # (possibly a NEW rank — the old slot may have
                            # been handed to our replacement already)
                            self.dp_rank = ray_tpu.get(
                                assigner.assign.remote(self.replica_id),
                                timeout=30)
                    except Exception:
                        pass  # assigner briefly unavailable; retry next tick
            threading.Thread(target=_renew_loop, daemon=True,
                             name="dp-rank-renew").start()

    async def __call__(self, body: dict) -> dict:
        out = await self._inner(body)
        out["dp_rank"] = self.dp_rank
        return out

    def rank(self) -> int:
        return self.dp_rank

    def shutdown(self):
        """Stop the lease renew loop and release the rank promptly (a
        killed replica's lease otherwise only frees after the TTL)."""
        self._stopped = True
        try:
            assigner = ray_tpu.get_actor(self._assigner_name)
            assigner.release.remote(self.replica_id)
        except Exception:
            pass  # TTL eviction reclaims the slot eventually

    def __del__(self):
        self._stopped = True


def build_dp_openai_app(config: LLMConfig, dp_size: int, params: Any = None
                        ) -> serve_api.DeploymentHandle:
    """Deploy dp_size engine replicas behind the serve router; each claims a
    dp rank from a named DPRankAssigner (reference: dp_server.py:14)."""
    params_blob = None
    if params is not None:
        import cloudpickle

        params_blob = cloudpickle.dumps(params)
    assigner_name = f"dp_assigner:{config.model_id}"
    # get-or-create: a redeploy must reuse the existing detached assigner
    # instead of silently colliding on the name
    ray_tpu.remote(num_cpus=0.1)(DPRankAssigner).options(
        name=assigner_name, lifetime="detached",
        get_if_exists=True).remote(dp_size)
    dep = serve_api.deployment(
        DPLLMServer, name=f"llm-dp:{config.model_id}", num_replicas=dp_size,
        max_ongoing_requests=config.engine_config.max_num_seqs * 2,
        ray_actor_options=dict(config.ray_actor_options) or {"num_cpus": 0.5})
    return serve_api.run(dep.bind(config, params_blob, assigner_name))

"""Inference forward passes with a paged KV cache (TPU-native vLLM core).

Reference capability: ray.llm serves models through vLLM's PagedAttention
engine (llm/_internal/serve/engines/vllm/vllm_engine.py). The TPU redesign
keeps the *cache geometry* idea — KV lives in fixed-shape pages, sequences
own pages through a block table — but implements it as pure-jnp programs so
every prefill bucket and the decode step are each ONE compiled XLA program
with static shapes (no dynamic shapes, no host sync inside the step).

Layout:
- ``k_pages``/``v_pages``: [n_layers, num_pages, page_size, n_kv_heads, hd]
- ``block_tables``:        [max_num_seqs, pages_per_seq] int32 page ids
- page 0 is scratch: masked-out writes (padding, inactive slots) land there.

The decode step gathers each slot's pages into a [B, Lmax] view and runs
grouped-query attention against it; the gather is a single XLA dynamic-gather
that TPUs handle well. A pallas paged-attention kernel can swap in underneath
without changing the cache layout.

Weights come from ``ray_tpu.models.transformer.Transformer`` — this module
reads the same param pytree (checkpoint-compatible with training).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig, _rope


class KVCache(NamedTuple):
    k: jax.Array  # [L, NP, P, KVH, HD]
    v: jax.Array


def init_cache(cfg: TransformerConfig, num_pages: int, page_size: int) -> KVCache:
    shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


# ---------------------------------------------------------------------------
# shared layer math (mirrors models/transformer.py, reading its param tree)
# ---------------------------------------------------------------------------


def _rmsnorm(x, scale, eps=1e-6):
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (norm * scale).astype(x.dtype)


def _mlp(x, p, dtype):
    gate = jnp.einsum("...d,df->...f", x, p["gate_proj"]["kernel"].astype(dtype))
    up = jnp.einsum("...d,df->...f", x, p["up_proj"]["kernel"].astype(dtype))
    hidden = jax.nn.silu(gate) * up
    return jnp.einsum("...f,fd->...d", hidden, p["down_proj"]["kernel"].astype(dtype))


def _qkv(x, p, cfg, positions):
    dtype = cfg.dtype
    q = jnp.einsum("...d,dhk->...hk", x, p["q_proj"]["kernel"].astype(dtype))
    k = jnp.einsum("...d,dhk->...hk", x, p["k_proj"]["kernel"].astype(dtype))
    v = jnp.einsum("...d,dhk->...hk", x, p["v_proj"]["kernel"].astype(dtype))
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def _scatter_kv(cache_layer, new, flat_idx):
    """Write new KV rows into the flat page view at flat_idx (0 = scratch)."""
    L_dims = cache_layer.shape  # (NP, P, KVH, HD)
    flat = cache_layer.reshape(L_dims[0] * L_dims[1], L_dims[2], L_dims[3])
    flat = flat.at[flat_idx.reshape(-1)].set(
        new.reshape(-1, new.shape[-2], new.shape[-1]), mode="drop")
    return flat.reshape(L_dims)


# ---------------------------------------------------------------------------
# prefill
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def prefill(params: Any, cfg: TransformerConfig, cache: KVCache,
            tokens: jax.Array, lengths: jax.Array,
            block_tables: jax.Array) -> Tuple[jax.Array, KVCache]:
    """Run the prompt forward, write KV pages, return last-position logits.

    tokens: [B, S] padded with PAD after `lengths`; block_tables: [B, MP].
    Returns logits [B, vocab] at position lengths-1 and the updated cache.
    """
    from ray_tpu.ops.attention import attention as attention_op

    p = params["params"]
    B, S = tokens.shape
    P = cache.k.shape[2]
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    in_prompt = positions < lengths[:, None]
    # padding tokens scatter to scratch page 0
    page_for = jnp.take_along_axis(
        block_tables, (positions // P).astype(jnp.int32), axis=1)
    flat_idx = jnp.where(in_prompt, page_for * P + positions % P, 0)

    x = p["embed"].astype(cfg.dtype)[tokens]
    new_k, new_v = cache.k, cache.v
    for i in range(cfg.n_layers):
        lp = p[f"layer_{i}"]
        h = _rmsnorm(x, lp["attn_norm"]["scale"])
        q, k, v = _qkv(h, lp["attn"], cfg, positions)
        new_k = new_k.at[i].set(_scatter_kv(new_k[i], k, flat_idx))
        new_v = new_v.at[i].set(_scatter_kv(new_v[i], v, flat_idx))
        if cfg.n_kv_heads != cfg.n_heads:
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        attn = attention_op(q, k, v, causal=True, impl=cfg.attention_impl)
        attn = jnp.einsum("...hk,hkd->...d",
                          attn, lp["attn"]["o_proj"]["kernel"].astype(cfg.dtype))
        h2 = x + attn
        x = h2 + _mlp(_rmsnorm(h2, lp["mlp_norm"]["scale"]), lp["mlp"], cfg.dtype)

    # hidden at the last prompt position only -> [B, d]
    last = jnp.take_along_axis(
        x, jnp.maximum(lengths - 1, 0)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    last = _rmsnorm(last, p["final_norm"]["scale"])
    if cfg.tie_embeddings:
        logits = jnp.einsum("bd,vd->bv", last, p["embed"].astype(cfg.dtype))
    else:
        logits = jnp.einsum("bd,dv->bv", last, p["lm_head"].astype(cfg.dtype),
                            preferred_element_type=jnp.float32)
    return logits.astype(jnp.float32), KVCache(new_k, new_v)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def decode_step(params: Any, cfg: TransformerConfig, cache: KVCache,
                last_tokens: jax.Array, seq_lens: jax.Array,
                block_tables: jax.Array, active: jax.Array
                ) -> Tuple[jax.Array, KVCache]:
    """One batched decode step over all slots: [B] tokens -> [B, vocab].

    Inactive slots compute garbage into scratch page 0. The new token's KV is
    written at position seq_lens before attention, so the mask is
    pos <= seq_lens.
    """
    p = params["params"]
    B = last_tokens.shape[0]
    L, NP, P, KVH, HD = cache.k.shape
    MP = block_tables.shape[1]
    Lmax = MP * P
    G = cfg.n_heads // cfg.n_kv_heads

    positions = seq_lens[:, None].astype(jnp.int32)  # [B, 1]
    cur_page = jnp.take_along_axis(block_tables, positions // P, axis=1)[:, 0]
    flat_write = jnp.where(active, cur_page * P + seq_lens % P, 0)[:, None]  # [B,1]
    # gather view: every slot's pages flattened to [B, Lmax]
    gather_idx = (block_tables[:, :, None] * P
                  + jnp.arange(P, dtype=jnp.int32)[None, None]).reshape(B, Lmax)
    kv_mask = (jnp.arange(Lmax, dtype=jnp.int32)[None] <= seq_lens[:, None]) \
        & active[:, None]
    scale = 1.0 / (HD ** 0.5)

    x = p["embed"].astype(cfg.dtype)[last_tokens[:, None]]  # [B, 1, d]
    new_k, new_v = cache.k, cache.v
    for i in range(cfg.n_layers):
        lp = p[f"layer_{i}"]
        h = _rmsnorm(x, lp["attn_norm"]["scale"])
        q, k, v = _qkv(h, lp["attn"], cfg, positions)  # q [B,1,H,hd]
        new_k = new_k.at[i].set(_scatter_kv(new_k[i], k, flat_write))
        new_v = new_v.at[i].set(_scatter_kv(new_v[i], v, flat_write))
        flat_k = new_k[i].reshape(NP * P, KVH, HD)
        flat_v = new_v[i].reshape(NP * P, KVH, HD)
        k_all = flat_k[gather_idx]  # [B, Lmax, KVH, HD]
        v_all = flat_v[gather_idx]
        # grouped-query attention without materializing repeated heads
        qg = q[:, 0].reshape(B, KVH, G, HD)
        scores = jnp.einsum("bkgd,blkd->bkgl", qg, k_all,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(kv_mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bkgl,blkd->bkgd", probs, v_all)
        attn = attn.reshape(B, 1, cfg.n_heads, HD)
        attn = jnp.einsum("...hk,hkd->...d",
                          attn, lp["attn"]["o_proj"]["kernel"].astype(cfg.dtype))
        h2 = x + attn
        x = h2 + _mlp(_rmsnorm(h2, lp["mlp_norm"]["scale"]), lp["mlp"], cfg.dtype)

    last = _rmsnorm(x[:, 0], p["final_norm"]["scale"])
    if cfg.tie_embeddings:
        logits = jnp.einsum("bd,vd->bv", last, p["embed"].astype(cfg.dtype))
    else:
        logits = jnp.einsum("bd,dv->bv", last, p["lm_head"].astype(cfg.dtype),
                            preferred_element_type=jnp.float32)
    return logits.astype(jnp.float32), KVCache(new_k, new_v)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("max_top_k",))
def sample_tokens(logits: jax.Array, rng: jax.Array, temps: jax.Array,
                  top_ks: jax.Array, top_ps: jax.Array,
                  seeds: jax.Array, steps: jax.Array,
                  max_top_k: int = 64) -> jax.Array:
    """Per-slot sampling: greedy when temp==0, else temp/top-k/top-p over a
    static top-``max_top_k`` shortlist (keeps the program shape static).

    ``seeds[b] >= 0`` gives that slot its own reproducible stream
    (PRNGKey(seed) folded with the slot's step count), independent of batch
    composition; ``seeds[b] < 0`` draws from the engine-global stream."""
    B, V = logits.shape
    K = min(max_top_k, V)
    greedy = jnp.argmax(logits, axis=-1)

    vals, idx = jax.lax.top_k(logits, K)  # [B, K] descending
    safe_t = jnp.maximum(temps, 1e-6)[:, None]
    scaled = vals / safe_t
    ranks = jnp.arange(K, dtype=jnp.int32)[None]
    k_lim = jnp.where(top_ks <= 0, K, jnp.minimum(top_ks, K))[:, None]
    mask = ranks < k_lim
    probs = jax.nn.softmax(jnp.where(mask, scaled, -1e30), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens whose cumulative prob before them is < top_p
    mask = mask & ((cum - probs) < top_ps[:, None])
    final = jnp.where(mask, scaled, -1e30)

    global_keys = jax.random.split(rng, B)
    seeded_keys = jax.vmap(
        lambda s, st: jax.random.fold_in(jax.random.PRNGKey(s), st)
    )(jnp.maximum(seeds, 0).astype(jnp.uint32), steps.astype(jnp.uint32))
    keys = jnp.where((seeds >= 0)[:, None], seeded_keys, global_keys)
    sampled_pos = jax.vmap(jax.random.categorical)(keys, final)
    sampled = jnp.take_along_axis(idx, sampled_pos[:, None], axis=1)[:, 0]
    return jnp.where(temps <= 0, greedy, sampled).astype(jnp.int32)

"""Tokenizers for the LLM layer.

``ByteTokenizer`` is the hermetic default (no downloads, vocab 256 + 3
specials) so CI and the tiny model run anywhere; HF tokenizers plug in by
name when available (reference: ray.llm resolves tokenizers via
transformers — llm/_internal/batch/stages/tokenize_stage.py).
"""

from __future__ import annotations

from typing import List, Sequence


class ByteTokenizer:
    """UTF-8 bytes shifted by the special-token count."""

    PAD = 0
    BOS = 1
    EOS = 2
    _SPECIALS = 3

    vocab_size = 256 + _SPECIALS

    @property
    def eos_token_id(self) -> int:
        return self.EOS

    @property
    def bos_token_id(self) -> int:
        return self.BOS

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = [b + self._SPECIALS for b in text.encode("utf-8")]
        return [self.BOS] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i - self._SPECIALS for i in ids
                     if i >= self._SPECIALS)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Thin adapter over a transformers tokenizer."""

    def __init__(self, name: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name)
        self.vocab_size = self._tok.vocab_size

    @property
    def eos_token_id(self) -> int:
        return self._tok.eos_token_id

    @property
    def bos_token_id(self) -> int:
        return self._tok.bos_token_id

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids)


def get_tokenizer(name: str):
    if name == "byte":
        return ByteTokenizer()
    return HFTokenizer(name)

"""LLM layer: TPU-native continuous-batching inference engine + serving.

Reference: python/ray/llm — LLMConfig (llm/_internal/serve/core/configs/
llm_config.py:141), vLLM engine wrapper (engines/vllm/vllm_engine.py),
OpenAI-compatible ingress, and batch-inference processors over Data
(llm/_internal/batch/processor/). The TPU-native redesign replaces the vLLM
CUDA engine with a JAX engine: paged KV cache in HBM, batched prefill and
single-token decode steps compiled once per shape bucket, continuous
batching in a host-side scheduler.

Heavy modules (jax) load lazily: importing ``ray_tpu.llm`` must stay cheap
for workers that only route requests.
"""

from ray_tpu.llm.config import LLMConfig, SamplingParams


def __getattr__(name):
    if name in ("JaxLLMEngine",):
        from ray_tpu.llm.engine import JaxLLMEngine

        return JaxLLMEngine
    if name in ("build_llm_deployment", "build_openai_app", "LLMServer"):
        from ray_tpu.llm import serve_llm

        return getattr(serve_llm, name)
    if name in ("build_llm_processor",):
        from ray_tpu.llm.data_llm import build_llm_processor

        return build_llm_processor
    raise AttributeError(name)


__all__ = [
    "LLMConfig",
    "SamplingParams",
    "JaxLLMEngine",
    "build_llm_deployment",
    "build_openai_app",
    "build_llm_processor",
]

"""Core microbenchmark suite (reference: python/ray/_private/ray_perf.py:95,
invoked as `ray microbenchmark`). Measures the owner-side submit path, actor
call throughput, and object plane bandwidth on the local cluster."""

from __future__ import annotations

import time
from typing import Callable, Dict, List

import numpy as np

import ray_tpu


def timeit(name: str, fn: Callable[[], int], duration: float = 2.0) -> Dict:
    # warmup
    fn()
    t0 = time.perf_counter()
    count = 0
    while time.perf_counter() - t0 < duration:
        count += fn()
    dt = time.perf_counter() - t0
    rate = count / dt
    return {"name": name, "rate_per_s": round(rate, 1)}


@ray_tpu.remote(num_cpus=0.2)
def _noop():
    return b"ok"


@ray_tpu.remote(num_cpus=0.2)
class _BenchActor:
    def noop(self):
        return b"ok"

    async def anoop(self):
        return b"ok"


def main(duration: float = 2.0) -> List[Dict]:
    results = []

    # tasks: sync round-trip and pipelined batches (ray_perf.py:176-191)
    results.append(timeit(
        "tasks_sync_per_s",
        lambda: (ray_tpu.get(_noop.remote(), timeout=60), 1)[1], duration))

    def batch_tasks():
        refs = [_noop.remote() for _ in range(100)]
        ray_tpu.get(refs, timeout=60)
        return 100

    results.append(timeit("tasks_async_batch_per_s", batch_tasks, duration))

    # deep pipeline, the reference's async-task shape (ray_perf.py keeps
    # ~1000 tasks in flight): amortizes the submit/complete barrier
    def pipeline_tasks():
        refs = [_noop.remote() for _ in range(1000)]
        ray_tpu.get(refs, timeout=120)
        return 1000

    results.append(timeit("tasks_pipeline1k_per_s", pipeline_tasks, duration))

    # actor calls 1:1 sync + async batches (ray_perf.py:198-243)
    actor = _BenchActor.remote()
    ray_tpu.get(actor.noop.remote(), timeout=60)
    results.append(timeit(
        "actor_calls_sync_per_s",
        lambda: (ray_tpu.get(actor.noop.remote(), timeout=60), 1)[1], duration))

    def batch_actor():
        refs = [actor.noop.remote() for _ in range(100)]
        ray_tpu.get(refs, timeout=60)
        return 100

    results.append(timeit("actor_calls_async_batch_per_s", batch_actor, duration))

    async_actor = _BenchActor.options(max_concurrency=8).remote()
    ray_tpu.get(async_actor.anoop.remote(), timeout=60)

    def batch_async_actor():
        refs = [async_actor.anoop.remote() for _ in range(100)]
        ray_tpu.get(refs, timeout=60)
        return 100

    results.append(timeit("async_actor_calls_batch_per_s", batch_async_actor,
                          duration))

    # object plane: small put/get and large-object bandwidth (ray_perf.py:122-148)
    small = {"k": 1}
    results.append(timeit(
        "put_small_per_s", lambda: (ray_tpu.put(small), 1)[1], duration))

    # numpy payload rides the out-of-band zero-copy path (shm-mapped on
    # read), like ray_perf.py's large-object cases
    big = np.frombuffer(np.random.bytes(10 * 1024 * 1024), dtype=np.uint8)

    def put_gig():
        ref = ray_tpu.put(big)
        ray_tpu.get(ref, timeout=120)
        return 1

    r = timeit("put_get_10MB_roundtrips_per_s", put_gig, duration)
    r["GB_per_s"] = round(r["rate_per_s"] * 10 * 2 / 1024, 3)
    results.append(r)

    ray_tpu.kill(actor)
    ray_tpu.kill(async_actor)
    return results


if __name__ == "__main__":
    ray_tpu.init()
    for row in main():
        print(row)
    ray_tpu.shutdown()

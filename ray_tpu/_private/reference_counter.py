"""Distributed reference counting for object ownership.

Reference: ``src/ray/core_worker/reference_counter.h:44`` — every object has
an owner (the worker that created it); the owner tracks local references,
in-flight submissions that depend on the object, and remote borrowers, and
frees the object cluster-wide when all reach zero. Lineage retention
(``task_manager.h:183``) pins task records while their outputs are
referenced so lost objects can be reconstructed by re-execution.

TPU-first deviations from the reference protocol:
- borrows are reported on the task reply (the executor lists foreign refs it
  still holds after the call) plus a debounced ``AddBorrower`` RPC for refs
  that arrive outside task args; a short grace period before the actual
  free absorbs in-flight registrations instead of the reference's full
  borrower-chain handshake;
- counts are process-wide per object id rather than per-handle.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple


class _Count:
    __slots__ = ("local", "pins", "borrowers", "lineage", "owner", "nested")

    def __init__(self, owner: str = ""):
        self.local = 0          # live ObjectRef instances in this process
        self.pins = 0           # in-flight handovers / stored-value nesting
        self.borrowers: Set[str] = set()  # remote holders (owner side)
        self.lineage = 0        # retained task records depending on this oid
        self.owner = owner      # owner address ("" = unknown yet)
        self.nested: List[Tuple[bytes, str]] = []  # inner refs we pin


class ReferenceCounter:
    """Process-wide object reference state.

    Thread-safe: ObjectRef __init__/__del__ fire on arbitrary threads; all
    free/borrow actions are deferred to the core worker's io loop through
    the ``on_zero`` / ``on_borrow_released`` callbacks.
    """

    def __init__(self, my_address: Callable[[], str]):
        import collections

        self._lock = threading.Lock()
        self._counts: Dict[bytes, _Count] = {}
        self._my_address = my_address
        # __del__-safe deletion queue: ObjectRef.__del__ may run via cyclic
        # GC on a thread that already holds self._lock (any allocation inside
        # a locked section can trigger GC) — taking the lock there would
        # self-deadlock. __del__ only appends here (deque.append is
        # GIL-atomic and reentrancy-safe); normal entry points drain it.
        self._pending_deletes: "collections.deque" = collections.deque()
        # zero-transition sink, installed by the core worker (borrow release
        # needs no sink: the OWNER observes it via its WaitBorrowsDone watch)
        self.on_owned_zero: Optional[Callable[[bytes], None]] = None
        # fired when a foreign-owned oid is first held here (0 -> 1)
        self.on_borrow_first: Optional[Callable[[bytes, str], None]] = None

    # -- ObjectRef lifecycle hooks (any thread) --

    def ref_created(self, oid: bytes, owner: str):
        self.flush_deletes()
        first_borrow = False
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                c = self._counts[oid] = _Count(owner)
            elif owner and not c.owner:
                c.owner = owner
            first_borrow = (c.local <= 0 and c.pins <= 0 and c.owner
                            and c.owner != self._my_address())
            c.local += 1
        if first_borrow and self.on_borrow_first is not None:
            self.on_borrow_first(oid, owner or "")

    def ref_deleted(self, oid: bytes):
        """Called from ObjectRef.__del__ — must NOT take the lock (see
        _pending_deletes). The decrement is applied at the next drain."""
        self._pending_deletes.append(oid)

    def flush_deletes(self):
        """Apply queued __del__ decrements. Called from normal (non-GC)
        entry points and the core worker's periodic sweep."""
        fires = []
        while True:
            try:
                oid = self._pending_deletes.popleft()
            except IndexError:
                break
            with self._lock:
                c = self._counts.get(oid)
                if c is None:
                    continue
                c.local -= 1
                if c.local <= 0 and c.pins <= 0:
                    kind = self._zero_kind(c)
                    if kind:
                        fires.append((kind, oid))
        for kind, oid in fires:
            self._fire(kind, oid)

    def _zero_kind(self, c: _Count):
        me = self._my_address()
        if not c.owner or c.owner == me:
            return "owned" if not c.borrowers else None
        return None  # borrow release: the owner's watch observes it

    def _fire(self, kind: Optional[str], oid: bytes):
        if kind == "owned" and self.on_owned_zero is not None:
            self.on_owned_zero(oid)

    # -- pins (handover / nesting; io loop or any thread) --

    def pin(self, oid: bytes, owner: str = ""):
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                c = self._counts[oid] = _Count(owner)
            elif owner and not c.owner:
                c.owner = owner
            c.pins += 1

    def unpin(self, oid: bytes):
        self.flush_deletes()
        fire = None
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                return
            c.pins -= 1
            if c.local <= 0 and c.pins <= 0:
                fire = self._zero_kind(c)
        self._fire(fire, oid)

    def pin_nested(self, outer: bytes, inner: List[Tuple[bytes, str]]):
        """Pin refs serialized inside a stored owned value until the outer
        object is freed (reference: nested refs in reference_counter.cc)."""
        if not inner:
            return
        with self._lock:
            c = self._counts.get(outer)
            if c is None:
                c = self._counts[outer] = _Count(self._my_address())
            c.nested.extend(inner)
        for oid, owner in inner:
            self.pin(oid, owner)

    def release_nested(self, outer: bytes) -> List[Tuple[bytes, str]]:
        with self._lock:
            c = self._counts.get(outer)
            if c is None or not c.nested:
                return []
            nested, c.nested = c.nested, []
        for oid, _ in nested:
            self.unpin(oid)
        return nested

    # -- borrowers (owner side, io loop) --

    def add_borrower(self, oid: bytes, address: str):
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                c = self._counts[oid] = _Count(self._my_address())
            c.borrowers.add(address)

    def remove_borrower(self, oid: bytes, address: str):
        self.flush_deletes()
        fire = None
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                return
            c.borrowers.discard(address)
            if c.local <= 0 and c.pins <= 0 and not c.borrowers:
                fire = self._zero_kind(c)
        self._fire(fire, oid)

    # -- lineage pinning --

    def lineage_add(self, oid: bytes):
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                c = self._counts[oid] = _Count()
            c.lineage += 1

    def lineage_remove(self, oid: bytes):
        with self._lock:
            c = self._counts.get(oid)
            if c is not None:
                c.lineage -= 1

    # -- queries --

    def local_count(self, oid: bytes) -> int:
        with self._lock:
            c = self._counts.get(oid)
            return 0 if c is None else c.local

    def held_count(self, oid: bytes) -> int:
        """Live handles + pins: what a borrow-done probe must see as zero
        (nested pins keep a borrow alive without any ObjectRef instance)."""
        with self._lock:
            c = self._counts.get(oid)
            return 0 if c is None else max(c.local, 0) + max(c.pins, 0)

    def borrowed_held(self) -> List[Tuple[bytes, str]]:
        """Foreign-owned oids this process still holds — the set a borrower
        periodically re-asserts with its owners (heals wrong reclaims)."""
        me = self._my_address()
        with self._lock:
            return [(oid, c.owner) for oid, c in self._counts.items()
                    if c.owner and c.owner != me
                    and (c.local > 0 or c.pins > 0)]

    def lineage_count(self, oid: bytes) -> int:
        with self._lock:
            c = self._counts.get(oid)
            return 0 if c is None else c.lineage

    def owner_of(self, oid: bytes) -> str:
        with self._lock:
            c = self._counts.get(oid)
            return "" if c is None else c.owner

    def freeable(self, oid: bytes) -> bool:
        """Owner-side re-check at actual free time (after the grace delay)."""
        self.flush_deletes()
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                return True
            return c.local <= 0 and c.pins <= 0 and not c.borrowers

    def drop(self, oid: bytes):
        with self._lock:
            self._counts.pop(oid, None)

    def stats(self) -> dict:
        with self._lock:
            return {
                "tracked": len(self._counts),
                "borrowed": sum(1 for c in self._counts.values()
                                if c.owner and c.owner != self._my_address()),
            }

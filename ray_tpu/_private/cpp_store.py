"""ctypes binding for the native arena object store (src/object_store).

Builds ``libray_tpu_store.so`` with g++ on first use (cached in build/);
the raylet's ObjectStoreServer uses it as the allocation backend when
available (config ``object_store_backend=auto|cpp|shm``). Workers map the
arena file directly for zero-copy reads/writes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "object_store", "store.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LIB = os.path.join(_BUILD_DIR, "libray_tpu_store.so")

_lock = threading.Lock()
_lib = None
_build_failed = False


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_LIB + ".tmp", _LIB)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError):
        return False


def load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_LIB):
            src_mtime = os.path.getmtime(_SRC) if os.path.exists(_SRC) else 0
            if not os.path.exists(_SRC) or not _build():
                _build_failed = True
                return None
        elif os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_LIB):
            _build()  # refresh; fall back to stale lib on failure
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build_failed = True
            return None
        lib.rts_open.restype = ctypes.c_void_p
        lib.rts_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.rts_close.argtypes = [ctypes.c_void_p]
        lib.rts_alloc.restype = ctypes.c_int
        lib.rts_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.POINTER(ctypes.c_uint64)]
        lib.rts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_lookup.restype = ctypes.c_int
        lib.rts_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_uint64),
                                   ctypes.POINTER(ctypes.c_uint64),
                                   ctypes.POINTER(ctypes.c_int)]
        lib.rts_free.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rts_free.restype = ctypes.c_int
        lib.rts_used.restype = ctypes.c_uint64
        lib.rts_used.argtypes = [ctypes.c_void_p]
        lib.rts_capacity.restype = ctypes.c_uint64
        lib.rts_capacity.argtypes = [ctypes.c_void_p]
        lib.rts_num_objects.restype = ctypes.c_uint64
        lib.rts_num_objects.argtypes = [ctypes.c_void_p]
        lib.rts_largest_free.restype = ctypes.c_uint64
        lib.rts_largest_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class CppArena:
    """Server-side handle to the native arena allocator."""

    def __init__(self, arena_name: str, capacity: int):
        lib = load_lib()
        if lib is None:
            raise RuntimeError("native store library unavailable")
        self.lib = lib
        self.arena_name = arena_name
        self.path = f"/dev/shm/{arena_name}"
        self.capacity = capacity
        self.handle = lib.rts_open(self.path.encode(), capacity, 1)
        if not self.handle:
            raise RuntimeError(f"failed to create arena {self.path}")

    def alloc(self, oid: bytes, size: int) -> Optional[int]:
        off = ctypes.c_uint64()
        rc = self.lib.rts_alloc(self.handle, oid, size, ctypes.byref(off))
        if rc == -2:
            return -2  # exists
        if rc != 0:
            return None
        return off.value

    def seal(self, oid: bytes) -> bool:
        return self.lib.rts_seal(self.handle, oid) == 0

    def lookup(self, oid: bytes) -> Optional[Tuple[int, int, bool]]:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        sealed = ctypes.c_int()
        if self.lib.rts_lookup(self.handle, oid, ctypes.byref(off),
                               ctypes.byref(size), ctypes.byref(sealed)) != 0:
            return None
        return off.value, size.value, bool(sealed.value)

    def free(self, oid: bytes) -> bool:
        return self.lib.rts_free(self.handle, oid) == 0

    def used(self) -> int:
        return self.lib.rts_used(self.handle)

    def num_objects(self) -> int:
        return self.lib.rts_num_objects(self.handle)

    def largest_free(self) -> int:
        return self.lib.rts_largest_free(self.handle)

    def close(self, unlink: bool = True):
        if self.handle:
            self.lib.rts_close(self.handle)
            self.handle = None
        if unlink:
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass

"""Global worker state and the public API implementation.

Reference: python/ray/_private/worker.py (``ray.init`` at :1432, ``ray.get``
:2863, ``ray.put`` :3010, ``ray.wait`` :3079, ``ray.remote`` :3564). Two
execution modes:

- local mode: tasks/actors execute inline in the driver process (reference's
  ``local_mode``) — used for debugging and fast unit tests.
- cluster mode: a ``CoreWorker`` connected to a GCS + raylet(s)
  (``ray_tpu/_private/core_worker.py``).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.common import ActorOptions, TaskOptions
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskError,
)
from ray_tpu.object_ref import ObjectRef

_global_worker = None
_lock = threading.RLock()


def global_worker():
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_worker


def is_initialized() -> bool:
    return _global_worker is not None


# ---------------------------------------------------------------------------
# Local mode
# ---------------------------------------------------------------------------


class LocalWorker:
    """Inline execution for debugging/tests (reference: local_mode)."""

    mode = "local"

    def __init__(self, namespace: str = "default"):
        self.job_id = JobID.from_int(1)
        self.namespace = namespace
        self._objects: Dict[ObjectID, Any] = {}
        self._actors: Dict[ActorID, Any] = {}
        self._actor_meta: Dict[ActorID, Tuple[str, str]] = {}  # id -> (name, ns)
        self._named: Dict[Tuple[str, str], ActorID] = {}
        self._put_index = 0
        self._task_id = TaskID.of(self.job_id)
        self.current_task_id = self._task_id
        self.current_actor_id: Optional[ActorID] = None

    # -- objects --
    def put(self, value: Any) -> ObjectRef:
        self._put_index += 1
        oid = ObjectID.from_put(self._task_id, self._put_index % 0x7FFF)
        self._objects[oid] = value
        return ObjectRef(oid)

    def _store_result(self, oid: ObjectID, value: Any):
        self._objects[oid] = value

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        out = []
        for ref in refs:
            if ref.id not in self._objects:
                raise GetTimeoutError(f"object {ref.hex()} not found in local mode")
            value = self._objects[ref.id]
            if isinstance(value, TaskError):
                raise value
            out.append(value)
        return out[0] if single else out

    def _run_coroutine(self, coro):
        """One persistent private loop: async actors may stash loop-bound
        futures across calls, and py3.12's get_event_loop() no longer
        conjures a loop in the main thread."""
        loop = getattr(self, "_loop", None)
        if loop is None or loop.is_closed():
            loop = self._loop = asyncio.new_event_loop()
        return loop.run_until_complete(coro)

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        ready = [r for r in refs if r.id in self._objects]
        return ready[:num_returns], [r for r in refs if r not in ready[:num_returns]]

    def as_future(self, ref) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        try:
            fut.set_result(self.get(ref))
        except Exception as e:
            fut.set_exception(e)
        return fut

    async def await_ref(self, ref):
        return self.get(ref)

    # -- tasks --
    def _resolve_args(self, args, kwargs):
        args = [self.get(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {
            k: self.get(v) if isinstance(v, ObjectRef) else v for k, v in kwargs.items()
        }
        return args, kwargs

    def _execute(self, fn, args, kwargs, num_returns: int, refs: List[ObjectRef]):
        try:
            args, kwargs = self._resolve_args(args, kwargs)
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = self._run_coroutine(result)
            if num_returns == 1:
                self._store_result(refs[0].id, result)
            else:
                values = list(result)
                for ref, v in zip(refs, values):
                    self._store_result(ref.id, v)
        except Exception as e:
            err = TaskError(repr(e), traceback.format_exc(), cause=e)
            for ref in refs:
                self._store_result(ref.id, err)

    def submit_task(self, remote_fn, args, kwargs, opts: TaskOptions):
        task_id = TaskID.of(self.job_id)
        refs = [
            ObjectRef(ObjectID.for_task_return(task_id, i))
            for i in range(opts.num_returns)
        ]
        self._execute(remote_fn.function, args, kwargs, opts.num_returns, refs)
        return refs[0] if opts.num_returns == 1 else refs

    # -- actors --
    def create_actor(self, actor_cls, args, kwargs, opts: ActorOptions):
        from ray_tpu.actor import ActorHandle

        if opts.name and opts.get_if_exists:
            key = (opts.namespace or self.namespace, opts.name)
            if key in self._named:
                aid = self._named[key]
                inst = self._actors[aid]
                return ActorHandle(aid, _instance_methods(inst), type(inst).__name__)
        actor_id = ActorID.of(self.job_id)
        args, kwargs = self._resolve_args(args, kwargs)
        instance = actor_cls.cls(*args, **kwargs)
        self._actors[actor_id] = instance
        if opts.name:
            key = (opts.namespace or self.namespace, opts.name)
            if key in self._named:
                raise ValueError(f"actor name {opts.name!r} already taken")
            self._named[key] = actor_id
            self._actor_meta[actor_id] = key
        return ActorHandle(actor_id, _instance_methods(instance), actor_cls.class_name)

    def submit_actor_task(self, handle, method_name, args, kwargs, num_returns=1,
                          tensor_transport=""):
        # local mode runs in-process: values are already "device-resident",
        # so the transport tag is a no-op
        if handle.actor_id not in self._actors:
            raise ActorDiedError(f"actor {handle.actor_id.hex()} is dead")
        instance = self._actors[handle.actor_id]
        task_id = TaskID.of(self.job_id)
        refs = [
            ObjectRef(ObjectID.for_task_return(task_id, i)) for i in range(num_returns)
        ]
        method = getattr(instance, method_name)
        prev = self.current_actor_id
        self.current_actor_id = handle.actor_id
        try:
            self._execute(method, args, kwargs, num_returns, refs)
        finally:
            self.current_actor_id = prev
        return refs[0] if num_returns == 1 else refs

    def get_actor(self, name: str, namespace: Optional[str] = None):
        from ray_tpu.actor import ActorHandle

        key = (namespace or self.namespace, name)
        if key not in self._named:
            raise ValueError(f"no actor named {name!r}")
        aid = self._named[key]
        inst = self._actors[aid]
        return ActorHandle(aid, _instance_methods(inst), type(inst).__name__)

    def kill_actor(self, handle, no_restart=True):
        self._actors.pop(handle.actor_id, None)
        key = self._actor_meta.pop(handle.actor_id, None)
        if key:
            self._named.pop(key, None)

    def cancel(self, ref, force=False, recursive=True):
        pass  # inline tasks already completed

    # -- cluster info --
    def cluster_resources(self):
        import os

        return {"CPU": float(os.cpu_count() or 1)}

    def available_resources(self):
        return self.cluster_resources()

    def nodes(self):
        return []

    def shutdown(self):
        self._objects.clear()
        self._actors.clear()
        self._named.clear()

    def free_objects(self, ids):
        for i in ids:
            self._objects.pop(i, None)


def _instance_methods(instance):
    return [
        n
        for n in dir(instance)
        if not n.startswith("__") and callable(getattr(instance, n, None))
    ]


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    local_mode: bool = False,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    object_store_memory: Optional[int] = None,
    log_to_driver: bool = True,
    runtime_env: Optional[Dict[str, Any]] = None,
    include_dashboard: bool = False,
    dashboard_port: Optional[int] = None,
    _system_config: Optional[Dict[str, Any]] = None,
):
    """Start (or connect to) a cluster and attach this process as the driver.

    Reference: ray.init (python/ray/_private/worker.py:1432).
    """
    global _global_worker
    with _lock:
        if _global_worker is not None:
            if ignore_reinit_error:
                return _global_worker
            raise RuntimeError("ray_tpu.init() already called (use ignore_reinit_error=True)")
        if address is None:
            # submitted jobs find their cluster through the environment
            # (reference: RAY_ADDRESS handling in ray.init)
            import os

            address = os.environ.get("RAY_TPU_ADDRESS") or None
        if _system_config:
            import os

            for k, v in _system_config.items():
                os.environ[f"RAY_TPU_{k.upper()}"] = str(v)
        if address and (address.startswith("ray-tpu://")
                        or address.startswith("ray_tpu://")):
            # client mode: thin external client -> in-cluster proxy
            # (reference: ray:// via python/ray/util/client)
            import logging as _logging

            unsupported = {"runtime_env": runtime_env, "num_cpus": num_cpus,
                           "num_tpus": num_tpus, "resources": resources,
                           "local_mode": local_mode or None}
            dropped = [k for k, v in unsupported.items() if v]
            if dropped:
                _logging.getLogger("ray_tpu").warning(
                    "client mode ignores init() options %s — set them on "
                    "the cluster/proxy side", dropped)
            from ray_tpu.util.client.client import ClientWorker

            _global_worker = ClientWorker(address, namespace=namespace)
            return _global_worker
        if local_mode:
            if runtime_env and runtime_env.get("env_vars"):
                import os

                os.environ.update(runtime_env["env_vars"])
            _global_worker = LocalWorker(namespace=namespace)
            return _global_worker
        from ray_tpu._private.core_worker import connect_driver

        _global_worker = connect_driver(
            address=address,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources or {},
            labels=labels or {},
            namespace=namespace,
            object_store_memory=object_store_memory,
            log_to_driver=log_to_driver,
            include_dashboard=include_dashboard,
            dashboard_port=dashboard_port,
        )
        if runtime_env:
            from ray_tpu._private.runtime_env import normalize

            # job-level default: merged into every task/actor whose options
            # don't set their own runtime_env
            _global_worker.job_runtime_env = normalize(runtime_env)
        from ray_tpu.util import tracing as _tracing

        if _tracing.enabled():
            # tracing must reach workers on pre-started clusters too: ride
            # the job runtime env (raylet merges env_vars into worker spawns)
            renv = dict(_global_worker.job_runtime_env or {})
            env_vars = dict(renv.get("env_vars") or {})
            env_vars.setdefault("RAY_TPU_ENABLE_TRACING", "1")
            renv["env_vars"] = env_vars
            _global_worker.job_runtime_env = renv
        return _global_worker


def shutdown():
    global _global_worker
    with _lock:
        if _global_worker is not None:
            _global_worker.shutdown()
            _global_worker = None


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() on an ObjectRef is not allowed")
    return global_worker().put(value)


def get(refs, *, timeout: Optional[float] = None):
    from ray_tpu.dag import CompiledDAGRef

    if isinstance(refs, CompiledDAGRef):
        return refs.get(timeout if timeout is not None else 300.0)
    if isinstance(refs, (list, tuple)):
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(f"get() expects ObjectRefs, got {type(bad[0]).__name__}")
    elif not isinstance(refs, ObjectRef):
        raise TypeError(f"get() expects an ObjectRef, got {type(refs).__name__}")
    return global_worker().get(refs, timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return global_worker().wait(
        list(refs), num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def kill(actor_handle, *, no_restart: bool = True):
    global_worker().kill_actor(actor_handle, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    global_worker().cancel(ref, force=force, recursive=recursive)


def get_actor(name: str, namespace: Optional[str] = None):
    return global_worker().get_actor(name, namespace)


def remote(*args, **kwargs):
    """@ray_tpu.remote decorator for functions and classes.

    Reference: ray.remote (python/ray/_private/worker.py:3564).
    """
    from ray_tpu.actor import ActorClass, build_actor_options
    from ray_tpu.remote_function import RemoteFunction, build_task_options

    def make(target, options):
        if inspect.isclass(target):
            return ActorClass(target, build_actor_options(ActorOptions(), options))
        if not callable(target):
            raise TypeError("@remote must decorate a function or class")
        opts = build_task_options(TaskOptions(), options)
        return RemoteFunction(target, opts)

    if len(args) == 1 and not kwargs and (callable(args[0]) or inspect.isclass(args[0])):
        return make(args[0], {})
    if args:
        raise TypeError("@remote options must be keyword arguments")

    def decorator(target):
        return make(target, kwargs)

    return decorator


def cluster_resources() -> Dict[str, float]:
    return global_worker().cluster_resources()


def available_resources() -> Dict[str, float]:
    return global_worker().available_resources()


def nodes() -> List[Dict[str, Any]]:
    return global_worker().nodes()

"""Node supervisor: starts/monitors the GCS and raylet processes.

Reference: python/ray/_private/node.py (``Node`` at :52) and services.py
process launchers (``start_gcs_server`` :1434, ``start_raylet`` :1518).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu._private.config import RAY_CONFIG

logger = logging.getLogger("ray_tpu.node")


def _wait_for_file(path: str, timeout: float = 30.0,
                   proc: Optional[subprocess.Popen] = None,
                   what: str = "service") -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                content = f.read().strip()
            if content:
                return content
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited with code {proc.returncode} before becoming "
                f"ready (see its log under the session logs directory)")
        time.sleep(0.02)
    raise TimeoutError(f"{what} did not write {path} in {timeout}s")


def new_session_dir() -> str:
    root = RAY_CONFIG.session_root
    session = os.path.join(root, f"session_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:6]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    latest = os.path.join(root, "session_latest")
    try:
        if os.path.islink(latest):
            os.unlink(latest)
        os.symlink(session, latest)
    except OSError:  # raylint: disable=EXC001 session_latest symlink is a convenience; racing starters may lose
        pass
    return session


class NodeSupervisor:
    """Launches a head node: GCS + one raylet (plus extra raylets for tests)."""

    def __init__(
        self,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
        gcs_fault_tolerance: bool = False,
    ):
        self.resources = resources or {}
        self.labels = labels or {}
        self.object_store_memory = object_store_memory
        self.session_dir = new_session_dir()
        self.log_dir = os.path.join(self.session_dir, "logs")
        self.processes: List[subprocess.Popen] = []
        self.gcs_address: Optional[str] = None
        self.gcs_fault_tolerance = gcs_fault_tolerance
        self.gcs_persist_dir = (
            os.path.join(self.session_dir, "gcs_store") if gcs_fault_tolerance else "")
        self.gcs_proc: Optional[subprocess.Popen] = None

    def _launch_gcs(self, port: int = 0) -> str:
        gcs_file = os.path.join(self.session_dir, f"gcs_address_{uuid.uuid4().hex[:6]}")
        cmd = [sys.executable, "-m", "ray_tpu._private.gcs",
               "--address-file", gcs_file, "--log-dir", self.log_dir]
        if port:
            cmd += ["--port", str(port)]
        if self.gcs_persist_dir:
            cmd += ["--persist-dir", self.gcs_persist_dir]
        self.gcs_proc = subprocess.Popen(
            cmd, stdout=self._log("gcs_out"), stderr=subprocess.STDOUT,
            env=self._child_env(),
        )
        self.processes.append(self.gcs_proc)
        return _wait_for_file(gcs_file)

    def start_head(self) -> str:
        self.gcs_address = self._launch_gcs()
        self.start_raylet(self.resources, self.labels, is_head=True)
        return self.gcs_address

    def start_dashboard(self, host: str = "127.0.0.1",
                        port: Optional[int] = None) -> str:
        """Launch the dashboard-lite head HTTP server (reference:
        dashboard/head.py started by services.py on the head node)."""
        assert self.gcs_address
        addr_file = os.path.join(self.session_dir, "dashboard_address")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.dashboard.head",
             "--gcs-address", self.gcs_address,
             "--host", host, "--port", str(port or 0),
             "--log-dir", self.log_dir,
             "--address-file", addr_file],
            stdout=self._log("dashboard_out"), stderr=subprocess.STDOUT,
            env=self._child_env(),
        )
        self.processes.append(proc)
        self.dashboard_address = _wait_for_file(addr_file, proc=proc,
                                                what="dashboard")
        return self.dashboard_address

    def kill_gcs(self):
        """Hard-kill the GCS process (fault-injection for FT tests)."""
        assert self.gcs_proc is not None
        self.gcs_proc.kill()
        self.gcs_proc.wait(timeout=10)
        self.processes.remove(self.gcs_proc)

    def restart_gcs(self) -> str:
        """Relaunch the GCS on the SAME address with the persisted tables
        (reference: GCS FT via Redis-backed store + GcsInitData replay)."""
        assert self.gcs_address and self.gcs_persist_dir, \
            "restart_gcs requires gcs_fault_tolerance=True"
        port = int(self.gcs_address.rsplit(":", 1)[1])
        addr = self._launch_gcs(port=port)
        assert addr == self.gcs_address, (addr, self.gcs_address)
        return addr

    def start_raylet(self, resources=None, labels=None, is_head=False,
                     object_store_memory=None) -> str:
        assert self.gcs_address
        addr_file = os.path.join(self.session_dir, f"raylet_{uuid.uuid4().hex[:8]}")
        cmd = [
            sys.executable, "-m", "ray_tpu._private.raylet",
            "--gcs-address", self.gcs_address,
            "--resources", json.dumps(resources or {}),
            "--labels", json.dumps(labels or {}),
            "--log-dir", self.log_dir,
            "--address-file", addr_file,
        ]
        if is_head:
            cmd.append("--head")
        osm = object_store_memory or self.object_store_memory
        if osm:
            cmd += ["--object-store-memory", str(int(osm))]
        proc = subprocess.Popen(cmd, stdout=self._log("raylet_out"),
                                stderr=subprocess.STDOUT,
                                env=self._child_env())
        self.processes.append(proc)
        return _wait_for_file(addr_file, timeout=60.0)

    def _child_env(self):
        return dict(os.environ, RAY_TPU_PARENT_PID=str(os.getpid()))

    def _log(self, name: str):
        return open(os.path.join(self.log_dir, f"{name}.log"), "ab")

    def stop(self):
        for proc in reversed(self.processes):
            try:
                proc.terminate()
            except Exception as e:
                logger.debug("terminate of pid %s failed: %s", proc.pid, e)
        deadline = time.monotonic() + 3.0
        for proc in self.processes:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                try:
                    proc.kill()
                except Exception as e:
                    logger.debug("kill of pid %s failed: %s", proc.pid, e)
        self.processes.clear()

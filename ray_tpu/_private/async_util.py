"""Small asyncio helpers shared across the runtime.

``spawn`` is the sanctioned way to start fire-and-forget background work on
an event loop (enforced by raylint rule ASY003): a bare
``asyncio.ensure_future(coro())`` whose result is never awaited, stored, or
given a done-callback silently swallows any exception the coroutine raises
(Python only logs it at garbage-collection time, often minutes later or
never) — on a control plane that turns a crashed scheduling loop into a
distributed hang with no trace. ``spawn`` attaches a done-callback that
retrieves and logs the failure immediately, with context.

Reference: the reference runtime's ``PeriodicalRunner`` / posted-task
error handling around its instrumented_io_context.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Optional

logger = logging.getLogger("ray_tpu.async")


def spawn(coro: Awaitable, what: str = "",
          log: Optional[logging.Logger] = None,
          loop: Optional[asyncio.AbstractEventLoop] = None) -> asyncio.Task:
    """Schedule ``coro`` as a background task with failure logging.

    Cancellation is not an error (shutdown cancels background work);
    any other exception is retrieved and logged with ``what`` as context,
    so background failures surface in the process log instead of dying
    with the task object.
    """
    if loop is not None:
        task = loop.create_task(coro)
    else:
        task = asyncio.ensure_future(coro)
    label = what or getattr(coro, "__qualname__", "background task")

    def _done(t: "asyncio.Task"):
        if t.cancelled():
            return
        exc = t.exception()  # also marks the exception as retrieved
        if exc is not None:
            (log or logger).warning("background task %r failed: %r",
                                    label, exc)

    task.add_done_callback(_done)
    return task

"""Asyncio RPC used by every control-plane and data-plane service.

Role-equivalent of the reference's gRPC wrappers (``src/ray/rpc``): a length-
prefixed msgpack envelope over TCP with request/response correlation,
automatic reconnect + retry with exponential backoff
(``retryable_grpc_client.h``), server->client push streams (used for pubsub,
like the reference's long-poll subscriber), and config-driven chaos injection
(``rpc/rpc_chaos.h``) so failure-handling paths are testable from day one.

Payloads are opaque bytes; control-plane callers encode them with the typed
wire schema (wire.py) — never pickle. Every frame carries the wire protocol
version; frames missing it or carrying a different version are rejected
before the payload is touched (reference: protobuf schema versioning in
``src/ray/protobuf/``).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import random
import time
from typing import Awaitable, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu._private.async_util import spawn
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.wire import WIRE_VERSION

logger = logging.getLogger(__name__)

_REQUEST, _REPLY_OK, _REPLY_ERR, _PUSH, _NOTIFY = 0, 1, 2, 3, 4

_MAX_FRAME = 1 << 31


class RpcError(Exception):
    pass


class RpcVersionError(RpcError):
    """Peer spoke a missing or different wire protocol version."""


class RpcConnectionError(RpcError):
    pass


class RpcNotConnectedError(RpcConnectionError):
    """Raised before any bytes were sent — always safe to retry, even for
    non-idempotent calls (the server never saw the request)."""


class RpcApplicationError(RpcError):
    """Remote handler raised; message carries the remote traceback string."""


# ---------------------------------------------------------------------------
# Chaos injection (reference: src/ray/rpc/rpc_chaos.h:24-39)
# ---------------------------------------------------------------------------


class _ChaosState:
    def __init__(self, spec: Optional[str] = None):
        self._counts: Dict[str, int] = {}
        self._spec: Dict[str, Tuple[int, float]] = {}
        if spec is None:
            spec = RAY_CONFIG.testing_rpc_failure
        if spec:
            for entry in spec.split(","):
                method, _, rest = entry.partition("=")
                n, _, p = rest.partition(":")
                self._spec[method.strip()] = (int(n or 0), float(p or 0.0))

    def should_fail(self, method: str) -> bool:
        if not self._spec:
            return False
        if method not in self._spec:
            return False
        n, p = self._spec[method]
        seen = self._counts.get(method, 0)
        self._counts[method] = seen + 1
        if seen < n:
            return True
        return random.random() < p


async def _maybe_chaos(chaos: _ChaosState, method: str):
    delay_ms = RAY_CONFIG.testing_rpc_delay_ms
    if delay_ms:
        await asyncio.sleep(delay_ms / 1000.0)
    if chaos.should_fail(method):
        raise RpcConnectionError(f"chaos: injected failure for {method}")


# ---------------------------------------------------------------------------
# Framing
# ---------------------------------------------------------------------------


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "big")
    if length > _MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    try:
        parts = msgpack.unpackb(body, raw=False, use_list=True)
    except Exception as e:
        raise RpcVersionError(f"unparseable frame (not wire msgpack): {e}")
    if not isinstance(parts, list) or len(parts) != 5 or parts[0] != WIRE_VERSION:
        got = parts[0] if isinstance(parts, list) and parts else "<none>"
        raise RpcVersionError(
            f"frame wire version {got!r} != {WIRE_VERSION} — peer is "
            f"unversioned or from an incompatible release")
    return parts[1:]


def _write_frame(writer: asyncio.StreamWriter, parts) -> None:
    body = msgpack.packb([WIRE_VERSION, *parts], use_bin_type=True)
    writer.write(len(body).to_bytes(4, "big") + body)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

Handler = Callable[[str, bytes, "ServerConnection"], Awaitable[bytes]]


class ServerConnection:
    """One accepted client connection; supports server->client pushes."""

    _ids = itertools.count(1)

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.conn_id = next(self._ids)
        self.closed = asyncio.Event()
        self._send_lock = asyncio.Lock()
        self.peer = writer.get_extra_info("peername")

    async def push(self, channel: str, payload: bytes) -> bool:
        if self.closed.is_set():
            return False
        try:
            async with self._send_lock:
                _write_frame(self.writer, [0, _PUSH, channel, payload])
                await self.writer.drain()
            return True
        except (ConnectionError, RuntimeError, OSError):
            self.closed.set()
            return False

    async def reply(self, msg_id: int, kind: int, payload: bytes):
        async with self._send_lock:
            _write_frame(self.writer, [msg_id, kind, "", payload])
            await self.writer.drain()


class RpcServer:
    def __init__(self, handler: Handler, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._host = host
        self._port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._chaos = _ChaosState()
        # reply-side chaos (reference rpc_chaos.h's reply-failure flavor):
        # the handler RUNS, then the connection drops before the reply —
        # produces zombie executions whose side effects raced a retry
        self._reply_chaos = _ChaosState(RAY_CONFIG.testing_rpc_reply_failure)
        self.connections: Dict[int, ServerConnection] = {}
        self.on_disconnect: Optional[Callable[[ServerConnection], Awaitable[None]]] = None

    @property
    def address(self) -> str:
        return f"{self._host}:{self._port}"

    async def start(self) -> str:
        self._server = await asyncio.start_server(self._on_client, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self.address

    async def stop(self):
        if self._server:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception as e:
                logger.debug("server wait_closed failed: %s", e)
        for conn in list(self.connections.values()):
            try:
                conn.writer.close()
            except Exception as e:
                logger.debug("closing connection to %s failed: %s",
                             conn.peer, e)

    async def _on_client(self, reader, writer):
        conn = ServerConnection(reader, writer)
        self.connections[conn.conn_id] = conn
        try:
            while True:
                msg_id, kind, method, payload = await _read_frame(reader)
                if kind == _NOTIFY:
                    spawn(self._dispatch(conn, None, method, payload),
                          what="rpc notify dispatch")
                elif kind == _REQUEST:
                    spawn(self._dispatch(conn, msg_id, method, payload),
                          what="rpc request dispatch")
        except RpcVersionError as e:
            logger.warning("dropping %s: %s", conn.peer, e)
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            logger.debug("connection from %s closed: %s", conn.peer, e)
        finally:
            conn.closed.set()
            self.connections.pop(conn.conn_id, None)
            if self.on_disconnect is not None:
                try:
                    await self.on_disconnect(conn)
                except Exception:
                    logger.exception("on_disconnect handler failed")
            try:
                writer.close()
            except Exception as e:
                logger.debug("writer close for %s failed: %s", conn.peer, e)

    async def _dispatch(self, conn, msg_id, method, payload):
        try:
            await _maybe_chaos(self._chaos, method)
            result = await self._handler(method, payload, conn)
            if self._reply_chaos.should_fail(method):
                conn.writer.close()
                conn.closed.set()
                return
            if msg_id is not None:
                await conn.reply(msg_id, _REPLY_OK, result if result is not None else b"")
        except Exception as e:
            if msg_id is not None:
                import traceback

                err = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                try:
                    await conn.reply(msg_id, _REPLY_ERR, err.encode())
                except Exception as e2:
                    logger.debug("error reply to %s undeliverable: %s",
                                 conn.peer, e2)
            else:
                logger.exception("error in one-way handler %s", method)


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class RpcClient:
    """Connection to one RpcServer; thread-compatible via the owning event loop."""

    def __init__(self, address: str, on_push: Optional[Callable] = None):
        self.address = address
        host, _, port = address.rpartition(":")
        self._host, self._port = host, int(port)
        self._reader = None
        self._writer = None
        self._msg_ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._on_push = on_push
        self._read_task = None
        self._lock = asyncio.Lock()
        self._closed = False
        self._chaos = _ChaosState()

    async def connect(self, timeout: Optional[float] = None):
        timeout = timeout or RAY_CONFIG.rpc_connect_timeout_s
        deadline = time.monotonic() + timeout
        delay = RAY_CONFIG.rpc_retry_base_delay_ms / 1000.0
        last = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                # bound each attempt too: a SYN blackhole (partitioned peer)
                # must not camp for the kernel retry timeout
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(self._host, self._port), remaining
                )
                self._read_task = asyncio.ensure_future(self._read_loop())
                return self
            except asyncio.TimeoutError:
                last = TimeoutError(f"connect attempt timed out after {remaining:.1f}s")
            except OSError as e:
                last = e
                await asyncio.sleep(min(delay, max(0.0, deadline - time.monotonic())))
                delay = min(delay * 2, RAY_CONFIG.rpc_retry_max_delay_ms / 1000.0)
        raise RpcNotConnectedError(f"cannot connect to {self.address}: {last}")

    @property
    def connected(self) -> bool:
        return self._writer is not None and not self._closed

    async def _read_loop(self):
        try:
            while True:
                msg_id, kind, method, payload = await _read_frame(self._reader)
                if kind == _PUSH:
                    if self._on_push is not None:
                        try:
                            res = self._on_push(method, payload)
                            if asyncio.iscoroutine(res):
                                spawn(res, what="push handler")
                        except Exception:
                            logger.exception("push handler failed")
                elif kind in (_REPLY_OK, _REPLY_ERR):
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        if kind == _REPLY_OK:
                            fut.set_result(payload)
                        else:
                            fut.set_exception(RpcApplicationError(payload.decode()))
        except RpcVersionError as e:
            self._fail_pending(e)
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self._fail_pending(RpcConnectionError(f"connection to {self.address} lost: {e}"))
        except asyncio.CancelledError:
            self._fail_pending(RpcConnectionError("client closed"))

    def _fail_pending(self, exc):
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    async def call(self, method: str, payload: bytes = b"", timeout: Optional[float] = None) -> bytes:
        await _maybe_chaos(self._chaos, method)
        if not self.connected:
            raise RpcNotConnectedError(f"not connected to {self.address}")
        msg_id = next(self._msg_ids)
        fut = asyncio.get_event_loop().create_future()
        self._pending[msg_id] = fut
        try:
            async with self._lock:
                _write_frame(self._writer, [msg_id, _REQUEST, method, payload])
                await self._writer.drain()
        except (ConnectionError, OSError) as e:
            self._pending.pop(msg_id, None)
            raise RpcConnectionError(str(e))
        timeout = timeout if timeout is not None else RAY_CONFIG.rpc_call_timeout_s
        return await asyncio.wait_for(fut, timeout)

    async def notify(self, method: str, payload: bytes = b""):
        if not self.connected:
            raise RpcConnectionError(f"not connected to {self.address}")
        async with self._lock:
            _write_frame(self._writer, [0, _NOTIFY, method, payload])
            await self._writer.drain()

    async def close(self):
        self._closed = True
        if self._read_task:
            self._read_task.cancel()
        if self._writer:
            try:
                self._writer.close()
            except Exception as e:
                logger.debug("client writer close failed: %s", e)


class RetryingRpcClient:
    """Reconnects and retries idempotent calls (reference: retryable_grpc_client.h)."""

    def __init__(self, address: str, on_push: Optional[Callable] = None,
                 on_reconnect: Optional[Callable] = None):
        self.address = address
        self._on_push = on_push
        self._on_reconnect = on_reconnect
        self._client: Optional[RpcClient] = None
        self._connect_lock: Optional[asyncio.Lock] = None

    async def _ensure(self, connect_timeout: Optional[float] = None) -> RpcClient:
        if self._connect_lock is None:
            self._connect_lock = asyncio.Lock()
        async with self._connect_lock:
            if self._client is None or not self._client.connected:
                client = RpcClient(self.address, on_push=self._on_push)
                try:
                    await client.connect(timeout=connect_timeout)
                    if self._on_reconnect is not None:
                        res = self._on_reconnect(client)
                        if asyncio.iscoroutine(res):
                            await res
                except BaseException:
                    # don't cache a client whose post-connect setup (e.g. a
                    # pubsub re-Subscribe) didn't finish — a cancelled
                    # on_reconnect would otherwise be skipped forever
                    await client.close()
                    raise
                self._client = client
        return self._client

    async def call(self, method: str, payload: bytes = b"", timeout: Optional[float] = None,
                   retries: Optional[int] = None,
                   connect_timeout: Optional[float] = None,
                   presend_retries: Optional[int] = None) -> bytes:
        retries = RAY_CONFIG.rpc_max_retries if retries is None else retries
        if presend_retries is None:
            presend_retries = max(retries, 3)
        delay = RAY_CONFIG.rpc_retry_base_delay_ms / 1000.0
        attempt = 0
        presend_attempt = 0
        presend_deadline = None

        async def _connected_client() -> RpcClient:
            budget = connect_timeout
            if presend_deadline is not None:
                remaining = presend_deadline - time.monotonic()
                budget = remaining if budget is None else min(budget, remaining)
                if budget <= 0:
                    raise RpcNotConnectedError(
                        f"connect budget exhausted for {self.address}")
            if budget is None:
                return await self._ensure(None)
            try:
                # bound the whole ensure — including the wait on the shared
                # connect lock — so one slow caller can't inflate another
                # caller's fail-fast bound on the same cached client
                return await asyncio.wait_for(self._ensure(budget), budget)
            except asyncio.TimeoutError:
                raise RpcNotConnectedError(f"connect to {self.address} timed out")

        while True:
            try:
                client = await _connected_client()
                return await client.call(method, payload, timeout)
            except RpcNotConnectedError:
                # nothing was sent (connect failed, or the connection dropped
                # before the frame went out): reconnect and retry without
                # consuming the caller's retry budget — non-idempotent calls
                # stay safe. Deadline-bounded so a dead peer still fails fast.
                if presend_deadline is None:
                    presend_deadline = (
                        time.monotonic() + RAY_CONFIG.rpc_presend_retry_timeout_s)
                presend_attempt += 1
                if presend_attempt > presend_retries \
                        or time.monotonic() + delay >= presend_deadline:
                    raise
                await asyncio.sleep(delay)
                delay = min(delay * 2, RAY_CONFIG.rpc_retry_max_delay_ms / 1000.0)
            except (RpcConnectionError, asyncio.TimeoutError):
                attempt += 1
                if attempt > retries:
                    raise
                await asyncio.sleep(delay)
                delay = min(delay * 2, RAY_CONFIG.rpc_retry_max_delay_ms / 1000.0)

    async def notify(self, method: str, payload: bytes = b""):
        client = await self._ensure()
        await client.notify(method, payload)

    async def close(self):
        if self._client:
            await self._client.close()
